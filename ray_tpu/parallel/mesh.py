"""Device-mesh construction for multi-dimensional parallelism.

The reference (Ray) has no first-class mesh concept — DP/TP/PP live in the
hosted frameworks (SURVEY.md §2.5, reference release/alpa_tests/).  Here the
mesh IS the first-class object: every parallelism strategy is an axis of one
`jax.sharding.Mesh` and XLA/GSPMD compiles the collectives onto ICI.

Axis vocabulary (MaxText-style, one mesh for the whole program):
  data    — pure data parallelism (batch split, gradients psum over ICI/DCN)
  fsdp    — data parallelism with sharded params/optimizer (ZeRO-3 style;
            params all-gathered per layer, grads reduce-scattered)
  expert  — expert parallelism for MoE layers (experts split across devices,
            tokens routed via all-to-all)
  seq     — sequence/context parallelism (ring attention over this axis)
  tensor  — tensor (megatron) parallelism within attention/mlp blocks
  stage   — pipeline stage axis (used by parallel.pipeline, not by GSPMD)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "expert", "seq", "tensor", "stage")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis; -1 means "absorb remaining devices".

    At most one axis may be -1.  The product of resolved sizes must equal the
    device count.
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    stage: int = 1

    def resolve(self, n_devices: int) -> dict:
        sizes = {a: getattr(self, a) for a in AXES}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes ({fixed})")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence[jax.Device]] = None,
                axis_names: Sequence[str] = AXES) -> Mesh:
    """Build a Mesh over `devices` (default: all) per `config`.

    Device order follows jax.devices(), which JAX arranges so that adjacent
    devices are ICI neighbours on TPU; trailing (fastest-varying) mesh axes
    therefore get the best ICI locality — put `tensor` and `seq` last, which
    the default axis order already does.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def create_two_level_mesh(
        ici: Optional[MeshConfig] = None,
        dcn: Optional[MeshConfig] = None,
        n_slices: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
        axis_names: Sequence[str] = AXES) -> Mesh:
    """Multi-slice (pod-to-pod) mesh: every logical axis is the product
    of a DCN part (across slices) and an ICI part (within a slice), with
    the DCN part slowest-varying — so walking any axis stays inside one
    slice until its ICI block is exhausted (SURVEY §2.5 "DCN collectives
    between slices", §7 P7).

    Lay DP (and optionally FSDP) on the DCN axes and keep TP/SP/EP
    strictly ICI: per-step DCN traffic is then one gradient
    reduce-scatter/all-gather, while the bandwidth-hungry activation
    collectives ride ICI.  XLA lowers a collective over a combined axis
    hierarchically when the device assignment is slice-contiguous (the
    megascale path on real multi-slice jobs; on the CPU simulator the
    topology is emulated but the assignment invariants are identical and
    are what the tests check).

    `devices` are grouped into `n_slices` equal contiguous blocks in
    order — matching jax.devices(), which sorts by (slice_index,
    on-slice coordinates) on real multi-slice TPU.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_slices <= 0 or len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices")
    per_slice = len(devices) // n_slices
    ici_sizes = (ici or MeshConfig()).resolve(per_slice)
    dcn_sizes = (dcn or MeshConfig(data=n_slices)).resolve(n_slices)
    for a in axis_names:
        if a in ("tensor", "seq", "expert") and dcn_sizes[a] > 1:
            raise ValueError(
                f"axis {a!r} must stay inside a slice (ICI): per-step "
                f"activation collectives over DCN would dominate the "
                f"step; shard it with the ici config instead")
    n_ax = len(axis_names)
    dev = np.asarray(devices).reshape(
        [dcn_sizes[a] for a in axis_names]
        + [ici_sizes[a] for a in axis_names])
    # Interleave (dcn_a, ici_a) per axis and merge: combined axis a has
    # the DCN part as the high-order digits.
    order = [i for pair in zip(range(n_ax), range(n_ax, 2 * n_ax))
             for i in pair]
    dev = dev.transpose(order).reshape(
        [dcn_sizes[a] * ici_sizes[a] for a in axis_names])
    return Mesh(dev, axis_names)


def slice_index_of(mesh: Mesh, n_slices: int) -> np.ndarray:
    """Map each mesh position to its slice id — the topology oracle the
    tests assert against: moving along an ICI-only axis must never
    change slice.  Real multi-slice TPUs expose device.slice_index; the
    simulator falls back to contiguous id blocks (the grouping
    create_two_level_mesh used)."""
    devs = np.asarray(mesh.devices)
    first = devs.reshape(-1)[0]
    if getattr(first, "slice_index", None) is not None:
        return np.vectorize(lambda d: d.slice_index)(devs)
    per_slice = devs.size // n_slices
    return np.vectorize(lambda d: d.id // per_slice)(devs)


def stage_slice_plan(n_gangs: int, n_slices: int) -> list:
    """Gang -> slice assignment for topology-aware pipeline placement.

    Gangs (pipeline stage-actor groups, `train.pipeline_trainer`) are
    packed into contiguous blocks per slice, so chunk hand-offs between
    gangs inside one block ride ICI and only block boundaries cross DCN
    — the multislice discipline `create_two_level_mesh` encodes for
    GSPMD programs, applied to the MPMD actor pipeline.  With the
    interleaved schedule (gang g owns chunks ``g, g+n_gangs, ...``)
    adjacent chunks are owned by adjacent gangs (mod n_gangs), so a
    contiguous gang block keeps adjacent chunks ICI-near by
    construction.

    Returns a list of length `n_gangs`: plan[g] = slice id.
    """
    if n_slices <= 0:
        raise ValueError(f"n_slices must be positive, got {n_slices}")
    if n_gangs % n_slices:
        raise ValueError(
            f"{n_gangs} gangs not divisible into {n_slices} slices — "
            f"unequal blocks would leave one slice's ICI underused")
    per = n_gangs // n_slices
    return [g // per for g in range(n_gangs)]


def dcn_cut_edges(plan: Sequence[int], n_chunks: int) -> list:
    """Chunk boundaries (c, c+1) whose hand-off crosses a DCN (slice)
    boundary under a gang->slice `plan` with round-robin chunk
    ownership (chunk c is owned by gang ``c % len(plan)``).

    This is the placement quality oracle: the pipeline should be cut at
    as few DCN edges as the slice count forces — ``len(plan)`` gangs in
    ``s`` slices force at least ``s - 1`` cuts per forward pass (plus
    interleave wraparounds), and a contiguous-block plan achieves that
    minimum for v=1."""
    n_gangs = len(plan)
    cuts = []
    for c in range(n_chunks - 1):
        if plan[c % n_gangs] != plan[(c + 1) % n_gangs]:
            cuts.append((c, c + 1))
    return cuts


def pipeline_placement_resources(plan: Sequence[int],
                                 prefix: str = "pp_slice_") -> list:
    """Per-gang custom-resource dicts realizing a `stage_slice_plan`:
    gang g's placement-group bundles demand ``{prefix}{plan[g]}: 1`` so
    its actors can only land on nodes advertising that slice resource
    (nodes declare e.g. ``resources={"pp_slice_0": 4}`` at start).
    Feed the result to ``PipelineTrainer(placement_plan=...)``."""
    return [{f"{prefix}{s}": 1} for s in plan]


def single_device_mesh() -> Mesh:
    """A 1-chip mesh with all axes size 1 — lets one jitted program serve
    both single-chip and pod runs without branching."""
    return create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions
    (the flag was renamed check_rep -> check_vma around jax 0.8)."""
    import inspect

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    kw = ("check_rep" if "check_rep"
          in inspect.signature(shard_map).parameters else "check_vma")
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **{kw: False})
