"""MPMD pipeline-parallel stage runtime: per-stage actor gangs.

Each pipeline *gang* is a group of actors under its own placement group
(an atomic slice reservation), running its own program — the MPMD shape
of arxiv 2412.14374, where the runtime (not XLA) owns the inter-stage
hop.  A gang owns one or more **stage-chunks** (the interleaved/looping
schedule: gang g owns chunks ``g, g+n_gangs, ...`` — non-adjacent, so
every gang computes during warmup/drain and the pipeline bubble shrinks
by ~1/v for v chunks per gang).  Activations and gradients cross chunks
as objects over the native shm-to-shm transfer plane: a chunk's
``forward`` returns the activation as a second return value whose
ObjectRef the driver hands to the next chunk *wrapped in a tuple*, so
the bytes move store-to-store and the receiving gang resolves them
inside a ``pp/xfer`` span (top-level args would be resolved by the task
layer before the method body runs, hiding the transfer from
attribution).

**Pre-pushed activations** take the transfer off the critical path: the
driver ships a sealed activation ref to the consumer's ``prefetch``
method the moment the producer's forward completes, while the consumer
is still computing an earlier microbatch.  ``prefetch`` rides the
actor's spare concurrency threads, resolves the ref inside a
``pp/xfer_overlap`` span, and parks the bytes in a bounded
**double-buffered receive window**; the consumer's ``forward`` then
takes the resident copy for free, waits briefly inside ``pp/recv_wait``
if the prefetch is still in flight, or falls back to the blocking
``pp/xfer`` fetch if nothing was pushed — so transfer time is either
hidden under compute or visibly attributed, never silently both.

Robustness contract (the reason MPMD beats the single-program dryrun in
`parallel/pipeline.py`): a gang dying must not tear down the pipeline.
All state a gang holds falls into three recovery classes:

- **params / optimizer version** — recovered from the gang's own
  sharded checkpoint (`checkpoint/` subsystem, COMMITTED steps only;
  one tree holding every owned chunk's params);
- **vjp residuals + per-microbatch grad contributions + the receive
  window** — process-local and unrecoverable, so the driver replays
  exactly the current step's microbatches through the re-formed gang,
  re-feeding (and re-pushing) the upstream chunks' still-sealed outputs
  (lineage through the object plane).  Prefetched-but-unconsumed
  activations are *replayable state*: the stage fns are deterministic,
  so a replayed producer reseals bit-identical bytes and a consumer
  holding the pre-kill copy cannot diverge;
- **activations already shipped downstream** — sealed in the node
  store, which survives worker death, so downstream chunks never
  recompute.

Grad contributions are kept **per chunk, per microbatch** and summed in
sorted microbatch order at update time, so a replayed (or interleaved)
schedule folds to bit-identical gradients regardless of completion
order.

The stage fns are framework-agnostic plain callables (cloudpickled to
the gang), so a numpy-only model keeps stage workers jax-free:

    stage_fwd(params, x)            -> (y, cache)
    stage_bwd(params, cache, gy)    -> (gx, gparams)
    loss_fwd(y, target)             -> (loss, lcache)
    loss_bwd(lcache)                -> gy

`pipeline_trainer.jax_stage_fns` builds the quartet from a jax
``stage_fn``/``loss_fn`` pair via ``jax.vjp``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.util.placement_group import (
    PlacementGroup, placement_group, remove_placement_group)

_M = None


def _metrics():
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "stall": mt.Histogram(
                "pp_stage_stall_seconds",
                "per-step idle seconds inside one stage worker (waiting "
                "on upstream activations, downstream grads, or recovery)",
                tag_keys=("stage",),
                buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                         2.5, 5.0, 10.0, 30.0, 60.0)),
        }
    return _M


def tree_map(fn: Callable, *trees):
    """jax.tree.map for the dict/list/tuple/leaf pytrees pipeline params
    use — kept local so stage workers never import jax for numpy models."""
    t0 = trees[0]
    if isinstance(t0, dict):
        return {k: tree_map(fn, *(t[k] for t in trees)) for k in t0}
    if isinstance(t0, (list, tuple)):
        seq = [tree_map(fn, *(t[i] for t in trees)) for i in range(len(t0))]
        return type(t0)(seq) if isinstance(t0, list) else tuple(seq)
    return fn(*trees)


def tree_add(a, b):
    return tree_map(lambda x, y: x + y, a, b)


@ray_tpu.remote
class PipelineStageActor:
    """One member of one gang.

    Methods that compute (`forward`/`backward`/`partial_grads`/
    `apply_update`) are dispatched at most one-at-a-time per member by
    the driver; `beacon`/`stats`/`prefetch` ride the actor's spare
    concurrency threads so liveness probes answer — and pre-pushed
    activations resolve — mid-compute (the PR 6 watchdog pattern,
    reused as the comm/compute overlap mechanism)."""

    def setup(self, spec: dict) -> bool:
        self.stage = int(spec["stage"])          # gang index
        self.n_stages = int(spec["n_stages"])    # total chunks end-to-end
        self.member = int(spec["member"])
        self.gang = int(spec["gang"])
        self.incarnation = int(spec.get("incarnation", 0))
        chunks = spec.get("chunks")
        if chunks is None:
            # Single-chunk legacy spec: the gang index IS the chunk.
            self.chunks = [self.stage]
            params = {self.stage: spec["params"]}
        else:
            self.chunks = sorted(int(c) for c in chunks)
            params = {int(c): t for c, t in spec["params"].items()}
        self._fwd = spec["stage_fwd"]
        self._bwd = spec["stage_bwd"]
        self._loss_fwd = spec.get("loss_fwd")
        self._loss_bwd = spec.get("loss_bwd")
        self.lr = float(spec["lr"])
        self.params = {c: tree_map(np.asarray, params[c])
                       for c in self.chunks}
        self.version = 0
        self._ckpt_mgr = None
        root = spec.get("ckpt_root") or ""
        if root:
            from ray_tpu.checkpoint import CheckpointManager
            self._ckpt_mgr = CheckpointManager(
                root, keep_last_k=int(spec.get("keep_last_k", 8)),
                save_id=f"s{self.stage}m{self.member}i{self.incarnation}")
        # Per-step state: vjp caches keyed (chunk, mb) + per-chunk
        # per-microbatch grad contributions.
        self._caches: Dict[Tuple[int, int], Any] = {}
        self._grads: Dict[int, Dict[int, Any]] = {c: {} for c in self.chunks}
        self._losses: Dict[int, float] = {}
        self._partial_cache = None
        # Double-buffered receive window: pre-pushed activations keyed
        # (step, chunk, mb).  prefetch() threads produce, forward()
        # consumes; the condition serializes the hand-off.  Consumed
        # keys are remembered so a late prefetch (forward already fell
        # back to the blocking fetch) is discarded, not leaked.
        self._recv_cv = threading.Condition()
        self._recv: Dict[Tuple[int, int, int], Any] = {}
        self._recv_pending: set = set()
        self._recv_err: Dict[Tuple[int, int, int], BaseException] = {}
        self._recv_consumed: set = set()
        self._recv_peak = 0
        self._recv_hits = 0
        self._recv_waits = 0
        self._recv_misses = 0
        self._prefetch_discards = 0
        self._recv_wait_timeout_s = float(
            spec.get("recv_wait_timeout_s", 30.0))
        # Bubble/stall accounting: gaps between ops inside one step.
        self._last_op_end = time.monotonic()
        self._busy_s = 0.0
        self._idle_s = 0.0
        self._ops = 0
        return True

    # ---------------- liveness / identity ----------------

    def beacon(self) -> dict:
        return {"stage": self.stage, "member": self.member,
                "version": self.version, "ops": self._ops,
                "age_s": time.monotonic() - self._last_op_end}

    def ident(self) -> dict:
        import os
        return {"pid": os.getpid(),
                "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
                "salt": os.environ.get("RAY_TPU_CHAOS_PROC_SALT", "")}

    def stats(self) -> dict:
        return {"stage": self.stage, "member": self.member,
                "busy_s": self._busy_s, "idle_s": self._idle_s,
                "ops": self._ops, "version": self.version,
                "chunks": list(self.chunks),
                "recv_peak": self._recv_peak,
                "recv_hits": self._recv_hits,
                "recv_waits": self._recv_waits,
                "recv_misses": self._recv_misses,
                "prefetch_discards": self._prefetch_discards}

    # ---------------- op bookkeeping ----------------

    def _op_begin(self) -> float:
        from ray_tpu.util import events
        now = time.monotonic()
        gap = now - self._last_op_end
        if gap > 1e-4:
            self._idle_s += gap
            events.record("pp", "bubble", stage=self.stage,
                          member=self.member, idle_s=round(gap, 6))
        return now

    def _op_end(self, t0: float) -> None:
        now = time.monotonic()
        self._busy_s += now - t0
        self._last_op_end = now
        self._ops += 1

    def _fetch(self, wrapped, what: str, chunk: Optional[int] = None):
        """Resolve a tuple-wrapped ObjectRef (or pass a raw value
        through) inside a pp/xfer span — the *blocking* inter-stage hop
        (the prefetch path resolves inside pp/xfer_overlap instead)."""
        if wrapped is None:
            return None
        (ref,) = wrapped
        if not isinstance(ref, ray_tpu.ObjectRef):
            return ref
        from ray_tpu.util import spans
        with spans.span("pp", "xfer", stage=self.stage, what=what,
                        chunk=chunk):
            return ray_tpu.get(ref)

    # ---------------- pre-pushed receive window ----------------

    def prefetch(self, step: int, chunk: int, mb: int, xw) -> dict:
        """Resolve a pre-pushed activation ref into the receive window.

        Runs on a spare concurrency thread while forward/backward
        compute on another, so `pp/xfer_overlap` elapses concurrently
        with compute instead of on the step's critical path.  Errors
        (e.g. the object died with a node) are parked for the consuming
        forward to re-raise — the driver's recovery then runs exactly as
        it would for a blocking-fetch failure."""
        from ray_tpu.util import spans
        key = (int(step), int(chunk), int(mb))
        with self._recv_cv:
            if (key in self._recv_consumed or key in self._recv
                    or key in self._recv_pending):
                # Late push after the consumer fell back to a blocking
                # fetch, or a replay re-push of a still-resident entry:
                # drop it (the sealed bytes are identical either way).
                self._prefetch_discards += 1
                return {"stored": False}
            self._recv_pending.add(key)
        val = err = None
        try:
            (ref,) = xw
            if isinstance(ref, ray_tpu.ObjectRef):
                with spans.span("pp", "xfer_overlap", stage=self.stage,
                                chunk=chunk, mb=mb):
                    val = ray_tpu.get(ref)
            else:
                val = ref
        except BaseException as e:       # parked, re-raised by forward
            err = e
        with self._recv_cv:
            self._recv_pending.discard(key)
            if key in self._recv_consumed:
                self._prefetch_discards += 1
            elif err is not None:
                self._recv_err[key] = err
            else:
                self._recv[key] = val
                # Peak residency per CHUNK — the observable the
                # backpressure bound governs (<= recv_window, +1 while
                # a consuming forward is mid-execution).
                depth = sum(1 for k in self._recv if k[1] == key[1])
                self._recv_peak = max(self._recv_peak, depth)
            self._recv_cv.notify_all()
        return {"stored": err is None}

    def _take_recv(self, step: int, chunk: int, mb: int, wrapped,
                   what: str):
        """Consume a pre-pushed activation if one is resident (or in
        flight, waiting inside pp/recv_wait); otherwise fall back to the
        blocking pp/xfer fetch of `wrapped`."""
        from ray_tpu.util import spans
        key = (step, chunk, mb)
        with self._recv_cv:
            if key not in self._recv and key not in self._recv_err \
                    and key in self._recv_pending:
                # Prefetch raced us: the bytes are mid-resolve on
                # another thread.  Wait bounded — a wedged prefetch
                # (never an expected state) degrades to the blocking
                # fetch instead of deadlocking the compute thread.
                self._recv_waits += 1
                tok = spans.begin("pp", "recv_wait", stage=self.stage,
                                  chunk=chunk, mb=mb)
                deadline = time.monotonic() + self._recv_wait_timeout_s
                while key in self._recv_pending \
                        and time.monotonic() < deadline:
                    self._recv_cv.wait(timeout=0.25)
                spans.end(tok)
            if key in self._recv:
                self._recv_hits += 1
                self._recv_consumed.add(key)
                return self._recv.pop(key)
            if key in self._recv_err:
                self._recv_consumed.add(key)
                raise self._recv_err.pop(key)
            self._recv_consumed.add(key)
            self._recv_misses += 1
        return self._fetch(wrapped, what, chunk=chunk)

    def _clear_recv(self):
        with self._recv_cv:
            self._recv.clear()
            self._recv_err.clear()
            self._recv_consumed.clear()
            # In-flight prefetches re-park after this clear; they are
            # keyed by (step, chunk, mb), so a stale entry can never be
            # consumed by a later step and the next clear drops it.

    # ---------------- compute ----------------

    def forward(self, step: int, chunk: int, mb: int, xw, tw=None):
        """One microbatch through one owned chunk.  Returns
        (meta, activation); the last chunk computes the loss chain
        instead and carries the scalar in meta (its second return is
        None)."""
        from ray_tpu.util import spans
        chunk = int(chunk)
        t0 = self._op_begin()
        x = self._take_recv(step, chunk, mb, xw, "act")
        last = chunk == self.n_stages - 1
        with spans.span("pp", "stage_fwd", stage=self.stage, chunk=chunk,
                        mb=mb, step=step):
            y, cache = self._fwd(self.params[chunk], x)
            if last:
                target = self._fetch(tw, "target", chunk=chunk)
                loss, lcache = self._loss_fwd(y, target)
                self._caches[(chunk, mb)] = (cache, lcache)
                self._losses[mb] = float(loss)
                self._op_end(t0)
                return ({"mb": mb, "step": step, "chunk": chunk,
                         "loss": float(loss), "version": self.version},
                        None)
        self._caches[(chunk, mb)] = cache
        self._op_end(t0)
        return ({"mb": mb, "step": step, "chunk": chunk,
                 "version": self.version}, np.asarray(y))

    def backward(self, step: int, chunk: int, mb: int, gyw=None):
        """Backward for one microbatch through one owned chunk: consumes
        the forward's cache, banks this (chunk, microbatch) param-grad
        contribution, and returns (meta, gx) — gx is the grad this chunk
        sends upstream."""
        from ray_tpu.util import spans
        chunk = int(chunk)
        t0 = self._op_begin()
        if (chunk, mb) not in self._caches:
            raise RuntimeError(
                f"gang {self.stage} has no forward cache for chunk "
                f"{chunk} microbatch {mb} (step {step}) — forward must "
                f"replay first")
        with spans.span("pp", "stage_bwd", stage=self.stage, chunk=chunk,
                        mb=mb, step=step):
            if chunk == self.n_stages - 1:
                cache, lcache = self._caches.pop((chunk, mb))
                gy = self._loss_bwd(lcache)
            else:
                cache = self._caches.pop((chunk, mb))
                gy = self._fetch(gyw, "grad", chunk=chunk)
            gx, gparams = self._bwd(self.params[chunk], cache, gy)
        self._grads[chunk][mb] = tree_map(np.asarray, gparams)
        self._op_end(t0)
        return ({"mb": mb, "step": step, "chunk": chunk,
                 "version": self.version}, np.asarray(gx))

    def partial_grads(self, step: int):
        """This member's summed grad contribution per owned chunk, each
        in sorted microbatch order (replay- and interleave-order
        independent).  Returns (meta, {chunk: grad_tree}).

        The sum is cached per step and survives apply_update: if the
        update boundary dies partway (some members applied, grads
        cleared), the retry still fetches identical partials from every
        member, so params never diverge across the gang."""
        if self._partial_cache is not None \
                and self._partial_cache[0] == step:
            totals = self._partial_cache[1]
            return ({"stage": self.stage, "member": self.member,
                     "step": step, "cached": True}, totals)
        t0 = self._op_begin()
        totals: Dict[int, Any] = {}
        for c in self.chunks:
            got = self._grads[c]
            if not got:
                raise RuntimeError(
                    f"gang {self.stage} member {self.member} has no grad "
                    f"contributions for chunk {c} at step {step}")
            order = sorted(got)
            total = got[order[0]]
            for j in order[1:]:
                total = tree_add(total, got[j])
            totals[c] = total
        self._partial_cache = (step, totals)
        self._op_end(t0)
        n = sum(len(self._grads[c]) for c in self.chunks)
        return ({"stage": self.stage, "member": self.member, "step": step,
                 "n_micro": n}, totals)

    def apply_update(self, step: int, grad_refs, n_micro: int) -> dict:
        """Fold the gang's partial grads (in member order — every member
        computes the identical per-chunk sum, so params stay replicated)
        and take one SGD step per owned chunk.  Version-guarded: a retry
        after this member already applied is a no-op, so recovery can
        never double-apply."""
        from ray_tpu.util import spans
        if self.version >= step + 1:
            return {"stage": self.stage, "member": self.member,
                    "version": self.version, "applied": False}
        t0 = self._op_begin()
        with spans.span("pp", "apply", stage=self.stage, step=step):
            totals = None
            for ref in grad_refs:
                g = self._fetch((ref,), "partial_grads")
                totals = g if totals is None else \
                    {c: tree_add(totals[c], g[c]) for c in totals}
            scale = 1.0 / float(n_micro)
            for c in self.chunks:
                self.params[c] = tree_map(
                    lambda p, g: p - self.lr * (g * scale),
                    self.params[c], totals[c])
        self.version = step + 1
        self._caches.clear()
        self._grads = {c: {} for c in self.chunks}
        self._losses.clear()
        self._clear_recv()
        _metrics()["stall"].observe(self._idle_s,
                                    tags={"stage": str(self.stage)})
        self._op_end(t0)
        busy, idle = self._busy_s, self._idle_s
        # Busy/idle are per-step: the driver derives the step's bubble
        # fraction from these, so reset at the update boundary.
        self._busy_s = 0.0
        self._idle_s = 0.0
        return {"stage": self.stage, "member": self.member,
                "version": self.version, "applied": True,
                "busy_s": busy, "idle_s": idle}

    def reset_step(self, step: int) -> bool:
        """Drop per-step state (rollback support: the step will replay)."""
        self._caches.clear()
        self._grads = {c: {} for c in self.chunks}
        self._losses.clear()
        self._partial_cache = None
        self._clear_recv()
        return True

    def reset_stats(self) -> dict:
        out = self.stats()
        self._busy_s = 0.0
        self._idle_s = 0.0
        self._last_op_end = time.monotonic()
        return out

    # ---------------- checkpoint ----------------

    def save_ckpt(self, step: int) -> bool:
        """Commit this gang's params+version as `step` (leader member
        only; params are replicated across the gang; one tree carries
        every owned chunk).  Waits for the COMMIT marker so the driver's
        boundary is durable."""
        if self._ckpt_mgr is None:
            return False
        from ray_tpu.util import spans
        with spans.span("pp", "ckpt", stage=self.stage, step=step):
            h = self._ckpt_mgr.save(
                step, {"params": {str(c): self.params[c]
                                  for c in self.chunks},
                       "version": self.version})
            h.wait(60)
        return True

    def load_ckpt(self, step: Optional[int] = None) -> Optional[int]:
        """Restore params+version from the latest COMMITTED step (or an
        exact step).  Returns the restored version, or None when nothing
        committed exists (caller falls back to initial params)."""
        if self._ckpt_mgr is None:
            return None
        target = step if step is not None else self._ckpt_mgr.latest_step()
        if target is None or target not in self._ckpt_mgr.steps():
            return None
        tree = self._ckpt_mgr.restore(target)
        p = tree["params"]
        if isinstance(p, dict) and set(p) == {str(c) for c in self.chunks}:
            self.params = {c: tree_map(np.asarray, p[str(c)])
                           for c in self.chunks}
        else:                            # single-chunk legacy tree
            self.params = {self.chunks[0]: tree_map(np.asarray, p)}
        self.version = int(tree["version"])
        self._caches.clear()
        self._grads = {c: {} for c in self.chunks}
        self._losses.clear()
        self._partial_cache = None
        self._clear_recv()
        return self.version

    def committed_steps(self) -> List[int]:
        if self._ckpt_mgr is None:
            return []
        return self._ckpt_mgr.steps()


class StageGroup:
    """One gang's actors under one placement group.

    Mirrors `WorkerGroup` (PG reserve -> actor construction -> identity
    resolution, with the same partial-failure cleanup: a half-built gang
    removes its just-created PG before re-raising, so elastic restarts
    can never leak reservations), but members are `PipelineStageActor`s
    and the group knows how to re-form in place: `reform()` builds a
    fresh gang (new PG, new actors via the zygote spawn path), bumps the
    incarnation so checkpoint save_ids never alias a dead gang's torn
    markers, and restores from the gang's latest COMMITTED checkpoint.

    Topology-aware placement rides `resources_per_worker`: the trainer
    merges a per-gang slice resource (e.g. ``{"pp_slice_0": 1}``, from
    `parallel.mesh.pipeline_placement_resources`) into the bundle specs,
    so a gang lands inside its assigned ICI slice and pipeline cuts fall
    only on DCN boundaries."""

    def __init__(self, stage: int, spec: dict, gang: int,
                 resources_per_worker: dict,
                 placement_strategy: str = "PACK",
                 pg_timeout_s: float = 60.0):
        self.stage = stage
        self.spec = dict(spec)
        self.gang = int(gang)
        self.resources = dict(resources_per_worker or {"CPU": 1})
        self.strategy = placement_strategy
        self.pg_timeout_s = pg_timeout_s
        self.incarnation = 0
        self._pg: Optional[PlacementGroup] = None
        self.members: List[Any] = []
        self.idents: List[dict] = []
        self._form()

    def _form(self):
        pg: Optional[PlacementGroup] = None
        members: List[Any] = []
        try:
            pg = placement_group(
                [dict(self.resources) for _ in range(self.gang)],
                strategy=self.strategy)
            if not pg.wait(self.pg_timeout_s):
                raise RuntimeError(
                    f"stage {self.stage}: could not reserve {self.gang} x "
                    f"{self.resources} within {self.pg_timeout_s:g}s")
            res = dict(self.resources)
            cpu = res.pop("CPU", 0)
            tpu = res.pop("TPU", None)
            # max_concurrency covers 1 compute op + the double-buffered
            # prefetch resolves per owned chunk + beacon probes.
            cls = PipelineStageActor.options(
                num_cpus=cpu, num_tpus=tpu, resources=res or None,
                max_concurrency=8)
            for m in range(self.gang):
                members.append(cls.options(
                    placement_group=pg,
                    placement_group_bundle_index=m).remote())
            spec = dict(self.spec)
            spec["gang"] = self.gang
            spec["incarnation"] = self.incarnation
            refs = []
            for m, actor in enumerate(members):
                s = dict(spec)
                s["member"] = m
                refs.append(actor.setup.remote(s))
            ray_tpu.get(refs, timeout=120)
            self.idents = ray_tpu.get(
                [a.ident.remote() for a in members], timeout=60)
        except BaseException:
            # Partial-failure hygiene: kill whatever booted and remove
            # the PG reservation before re-raising.
            for a in members:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            if pg is not None:
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass
            raise
        self._pg = pg
        self.members = members

    def reform(self) -> Optional[int]:
        """Tear down and rebuild this gang in place; restore from the
        gang's latest COMMITTED checkpoint.  Returns the restored
        version (None = nothing committed; members hold initial params)."""
        self.shutdown()
        self.incarnation += 1
        self._form()
        versions = ray_tpu.get(
            [a.load_ckpt.remote() for a in self.members], timeout=120)
        vs = {v for v in versions}
        if len(vs) != 1:
            # Members disagree (a commit raced a member's scan): converge
            # on the lowest common committed version.
            steps = ray_tpu.get(
                [a.committed_steps.remote() for a in self.members],
                timeout=60)
            common = set(steps[0]).intersection(*map(set, steps[1:])) \
                if steps else set()
            if not common:
                return None
            tgt = max(common)
            ray_tpu.get([a.load_ckpt.remote(tgt) for a in self.members],
                        timeout=120)
            return tgt
        return vs.pop()

    def beacons(self, timeout: float = 5.0) -> List[Optional[dict]]:
        """Best-effort liveness snapshot; None per member that did not
        answer (dead, or wedged past the probe timeout)."""
        refs = {a.beacon.remote(): m for m, a in enumerate(self.members)}
        out: List[Optional[dict]] = [None] * len(self.members)
        ready, _ = ray_tpu.wait(list(refs), num_returns=len(refs),
                                timeout=timeout)
        for r in ready:
            try:
                out[refs[r]] = ray_tpu.get(r)
            except Exception:
                pass
        return out

    def shutdown(self):
        for a in self.members:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self.members = []
        self.idents = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
