"""MPMD pipeline-parallel stage runtime: per-stage actor gangs.

Each pipeline stage is its own gang of actors under its own placement
group (an atomic slice reservation), running its own program — the MPMD
shape of arxiv 2412.14374, where the runtime (not XLA) owns the
inter-stage hop.  Activations and gradients cross stages as objects over
the native shm-to-shm transfer plane: a stage's ``forward`` returns the
activation as a second return value whose ObjectRef the driver hands to
the next stage *wrapped in a tuple*, so the bytes move store-to-store and
the receiving stage resolves them inside a ``pp/xfer`` span (top-level
args would be resolved by the task layer before the method body runs,
hiding the transfer from attribution).

Robustness contract (the reason MPMD beats the single-program dryrun in
`parallel/pipeline.py`): a stage gang dying must not tear down the
pipeline.  All state a stage holds falls into three recovery classes:

- **params / optimizer version** — recovered from the stage's own
  sharded checkpoint (`checkpoint/` subsystem, COMMITTED steps only);
- **vjp residuals + per-microbatch grad contributions** — process-local
  and unrecoverable, so the driver replays exactly the current step's
  microbatches through the re-formed gang, re-feeding the upstream
  stage's still-sealed outputs (lineage through the object plane);
- **activations already shipped downstream** — sealed in the node store,
  which survives worker death, so downstream stages never recompute.

Grad contributions are kept **per microbatch** and summed in sorted
microbatch order at update time, so a replayed schedule folds to
bit-identical gradients regardless of completion order.

The stage fns are framework-agnostic plain callables (cloudpickled to
the gang), so a numpy-only model keeps stage workers jax-free:

    stage_fwd(params, x)            -> (y, cache)
    stage_bwd(params, cache, gy)    -> (gx, gparams)
    loss_fwd(y, target)             -> (loss, lcache)
    loss_bwd(lcache)                -> gy

`pipeline_trainer.jax_stage_fns` builds the quartet from a jax
``stage_fn``/``loss_fn`` pair via ``jax.vjp``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.util.placement_group import (
    PlacementGroup, placement_group, remove_placement_group)

_M = None


def _metrics():
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "stall": mt.Histogram(
                "pp_stage_stall_seconds",
                "per-step idle seconds inside one stage worker (waiting "
                "on upstream activations, downstream grads, or recovery)",
                tag_keys=("stage",),
                buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                         2.5, 5.0, 10.0, 30.0, 60.0)),
        }
    return _M


def tree_map(fn: Callable, *trees):
    """jax.tree.map for the dict/list/tuple/leaf pytrees pipeline params
    use — kept local so stage workers never import jax for numpy models."""
    t0 = trees[0]
    if isinstance(t0, dict):
        return {k: tree_map(fn, *(t[k] for t in trees)) for k in t0}
    if isinstance(t0, (list, tuple)):
        seq = [tree_map(fn, *(t[i] for t in trees)) for i in range(len(t0))]
        return type(t0)(seq) if isinstance(t0, list) else tuple(seq)
    return fn(*trees)


def tree_add(a, b):
    return tree_map(lambda x, y: x + y, a, b)


@ray_tpu.remote
class PipelineStageActor:
    """One member of one stage's gang.

    Methods that compute (`forward`/`backward`/`partial_grads`/
    `apply_update`) are dispatched at most one-at-a-time per member by
    the driver; `beacon`/`stats` ride the actor's spare concurrency
    threads so liveness probes answer mid-compute (the PR 6 watchdog
    pattern)."""

    def setup(self, spec: dict) -> bool:
        self.stage = int(spec["stage"])
        self.n_stages = int(spec["n_stages"])
        self.member = int(spec["member"])
        self.gang = int(spec["gang"])
        self.incarnation = int(spec.get("incarnation", 0))
        self._fwd = spec["stage_fwd"]
        self._bwd = spec["stage_bwd"]
        self._loss_fwd = spec.get("loss_fwd")
        self._loss_bwd = spec.get("loss_bwd")
        self.lr = float(spec["lr"])
        self.params = tree_map(np.asarray, spec["params"])
        self.version = 0
        self._ckpt_mgr = None
        root = spec.get("ckpt_root") or ""
        if root:
            from ray_tpu.checkpoint import CheckpointManager
            self._ckpt_mgr = CheckpointManager(
                root, keep_last_k=int(spec.get("keep_last_k", 8)),
                save_id=f"s{self.stage}m{self.member}i{self.incarnation}")
        # Per-step state: vjp caches + per-microbatch grad contributions.
        self._caches: Dict[int, Any] = {}
        self._grads: Dict[int, Any] = {}
        self._losses: Dict[int, float] = {}
        self._partial_cache = None
        # Bubble/stall accounting: gaps between ops inside one step.
        self._last_op_end = time.monotonic()
        self._busy_s = 0.0
        self._idle_s = 0.0
        self._ops = 0
        return True

    # ---------------- liveness / identity ----------------

    def beacon(self) -> dict:
        return {"stage": self.stage, "member": self.member,
                "version": self.version, "ops": self._ops,
                "age_s": time.monotonic() - self._last_op_end}

    def ident(self) -> dict:
        import os
        return {"pid": os.getpid(),
                "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
                "salt": os.environ.get("RAY_TPU_CHAOS_PROC_SALT", "")}

    def stats(self) -> dict:
        return {"stage": self.stage, "member": self.member,
                "busy_s": self._busy_s, "idle_s": self._idle_s,
                "ops": self._ops, "version": self.version}

    # ---------------- op bookkeeping ----------------

    def _op_begin(self) -> float:
        from ray_tpu.util import events
        now = time.monotonic()
        gap = now - self._last_op_end
        if gap > 1e-4:
            self._idle_s += gap
            events.record("pp", "bubble", stage=self.stage,
                          member=self.member, idle_s=round(gap, 6))
        return now

    def _op_end(self, t0: float) -> None:
        now = time.monotonic()
        self._busy_s += now - t0
        self._last_op_end = now
        self._ops += 1

    def _fetch(self, wrapped, what: str):
        """Resolve a tuple-wrapped ObjectRef (or pass a raw value
        through) inside a pp/xfer span — the inter-stage hop."""
        if wrapped is None:
            return None
        (ref,) = wrapped
        if not isinstance(ref, ray_tpu.ObjectRef):
            return ref
        from ray_tpu.util import spans
        with spans.span("pp", "xfer", stage=self.stage, what=what):
            return ray_tpu.get(ref)

    # ---------------- compute ----------------

    def forward(self, step: int, mb: int, xw, tw=None):
        """One microbatch through this stage.  Returns (meta, activation);
        the last stage computes the loss chain instead and carries the
        scalar in meta (its second return is None)."""
        from ray_tpu.util import spans
        t0 = self._op_begin()
        x = self._fetch(xw, "act")
        last = self.stage == self.n_stages - 1
        with spans.span("pp", "stage_fwd", stage=self.stage, mb=mb,
                        step=step):
            y, cache = self._fwd(self.params, x)
            if last:
                target = self._fetch(tw, "target")
                loss, lcache = self._loss_fwd(y, target)
                self._caches[mb] = (cache, lcache)
                self._losses[mb] = float(loss)
                self._op_end(t0)
                return ({"mb": mb, "step": step, "loss": float(loss),
                         "version": self.version}, None)
        self._caches[mb] = cache
        self._op_end(t0)
        return ({"mb": mb, "step": step, "version": self.version},
                np.asarray(y))

    def backward(self, step: int, mb: int, gyw=None):
        """Backward for one microbatch: consumes the forward's cache,
        banks this microbatch's param-grad contribution, and returns
        (meta, gx) — gx is the grad this stage sends upstream."""
        from ray_tpu.util import spans
        t0 = self._op_begin()
        if mb not in self._caches:
            raise RuntimeError(
                f"stage {self.stage} has no forward cache for microbatch "
                f"{mb} (step {step}) — forward must replay first")
        with spans.span("pp", "stage_bwd", stage=self.stage, mb=mb,
                        step=step):
            if self.stage == self.n_stages - 1:
                cache, lcache = self._caches.pop(mb)
                gy = self._loss_bwd(lcache)
            else:
                cache = self._caches.pop(mb)
                gy = self._fetch(gyw, "grad")
            gx, gparams = self._bwd(self.params, cache, gy)
        self._grads[mb] = tree_map(np.asarray, gparams)
        self._op_end(t0)
        return ({"mb": mb, "step": step, "version": self.version},
                np.asarray(gx))

    def partial_grads(self, step: int):
        """This member's summed grad contribution, in sorted microbatch
        order (replay-order independent).  Returns (meta, grad_tree).

        The sum is cached per step and survives apply_update: if the
        update boundary dies partway (some members applied, grads
        cleared), the retry still fetches identical partials from every
        member, so params never diverge across the gang."""
        if self._partial_cache is not None \
                and self._partial_cache[0] == step:
            total = self._partial_cache[1]
            return ({"stage": self.stage, "member": self.member,
                     "step": step, "cached": True}, total)
        t0 = self._op_begin()
        if not self._grads:
            raise RuntimeError(
                f"stage {self.stage} member {self.member} has no grad "
                f"contributions for step {step}")
        order = sorted(self._grads)
        total = self._grads[order[0]]
        for j in order[1:]:
            total = tree_add(total, self._grads[j])
        self._partial_cache = (step, total)
        self._op_end(t0)
        return ({"stage": self.stage, "member": self.member, "step": step,
                 "n_micro": len(order)}, total)

    def apply_update(self, step: int, grad_refs, n_micro: int) -> dict:
        """Fold the gang's partial grads (in member order — every member
        computes the identical sum, so params stay replicated) and take
        one SGD step.  Version-guarded: a retry after this member already
        applied is a no-op, so recovery can never double-apply."""
        from ray_tpu.util import spans
        if self.version >= step + 1:
            return {"stage": self.stage, "member": self.member,
                    "version": self.version, "applied": False}
        t0 = self._op_begin()
        with spans.span("pp", "apply", stage=self.stage, step=step):
            total = None
            for ref in grad_refs:
                g = self._fetch((ref,), "partial_grads")
                total = g if total is None else tree_add(total, g)
            scale = 1.0 / float(n_micro)
            self.params = tree_map(
                lambda p, g: p - self.lr * (g * scale), self.params, total)
        self.version = step + 1
        self._caches.clear()
        self._grads.clear()
        self._losses.clear()
        _metrics()["stall"].observe(self._idle_s,
                                    tags={"stage": str(self.stage)})
        self._op_end(t0)
        busy, idle = self._busy_s, self._idle_s
        # Busy/idle are per-step: the driver derives the step's bubble
        # fraction from these, so reset at the update boundary.
        self._busy_s = 0.0
        self._idle_s = 0.0
        return {"stage": self.stage, "member": self.member,
                "version": self.version, "applied": True,
                "busy_s": busy, "idle_s": idle}

    def reset_step(self, step: int) -> bool:
        """Drop per-step state (rollback support: the step will replay)."""
        self._caches.clear()
        self._grads.clear()
        self._losses.clear()
        self._partial_cache = None
        return True

    def reset_stats(self) -> dict:
        out = self.stats()
        self._busy_s = 0.0
        self._idle_s = 0.0
        self._last_op_end = time.monotonic()
        return out

    # ---------------- checkpoint ----------------

    def save_ckpt(self, step: int) -> bool:
        """Commit this stage's params+version as `step` (leader member
        only; params are replicated across the gang).  Waits for the
        COMMIT marker so the driver's boundary is durable."""
        if self._ckpt_mgr is None:
            return False
        from ray_tpu.util import spans
        with spans.span("pp", "ckpt", stage=self.stage, step=step):
            h = self._ckpt_mgr.save(
                step, {"params": self.params, "version": self.version})
            h.wait(60)
        return True

    def load_ckpt(self, step: Optional[int] = None) -> Optional[int]:
        """Restore params+version from the latest COMMITTED step (or an
        exact step).  Returns the restored version, or None when nothing
        committed exists (caller falls back to initial params)."""
        if self._ckpt_mgr is None:
            return None
        target = step if step is not None else self._ckpt_mgr.latest_step()
        if target is None or target not in self._ckpt_mgr.steps():
            return None
        tree = self._ckpt_mgr.restore(target)
        self.params = tree_map(np.asarray, tree["params"])
        self.version = int(tree["version"])
        self._caches.clear()
        self._grads.clear()
        self._losses.clear()
        self._partial_cache = None
        return self.version

    def committed_steps(self) -> List[int]:
        if self._ckpt_mgr is None:
            return []
        return self._ckpt_mgr.steps()


class StageGroup:
    """One pipeline stage's actor gang under one placement group.

    Mirrors `WorkerGroup` (PG reserve -> actor construction -> identity
    resolution, with the same partial-failure cleanup: a half-built gang
    removes its just-created PG before re-raising, so elastic restarts
    can never leak reservations), but members are `PipelineStageActor`s
    and the group knows how to re-form in place: `reform()` builds a
    fresh gang (new PG, new actors via the zygote spawn path), bumps the
    incarnation so checkpoint save_ids never alias a dead gang's torn
    markers, and restores from the stage's latest COMMITTED checkpoint."""

    def __init__(self, stage: int, spec: dict, gang: int,
                 resources_per_worker: dict,
                 placement_strategy: str = "PACK",
                 pg_timeout_s: float = 60.0):
        self.stage = stage
        self.spec = dict(spec)
        self.gang = int(gang)
        self.resources = dict(resources_per_worker or {"CPU": 1})
        self.strategy = placement_strategy
        self.pg_timeout_s = pg_timeout_s
        self.incarnation = 0
        self._pg: Optional[PlacementGroup] = None
        self.members: List[Any] = []
        self.idents: List[dict] = []
        self._form()

    def _form(self):
        pg: Optional[PlacementGroup] = None
        members: List[Any] = []
        try:
            pg = placement_group(
                [dict(self.resources) for _ in range(self.gang)],
                strategy=self.strategy)
            if not pg.wait(self.pg_timeout_s):
                raise RuntimeError(
                    f"stage {self.stage}: could not reserve {self.gang} x "
                    f"{self.resources} within {self.pg_timeout_s:g}s")
            res = dict(self.resources)
            cpu = res.pop("CPU", 0)
            tpu = res.pop("TPU", None)
            cls = PipelineStageActor.options(
                num_cpus=cpu, num_tpus=tpu, resources=res or None,
                max_concurrency=4)
            for m in range(self.gang):
                members.append(cls.options(
                    placement_group=pg,
                    placement_group_bundle_index=m).remote())
            spec = dict(self.spec)
            spec["gang"] = self.gang
            spec["incarnation"] = self.incarnation
            refs = []
            for m, actor in enumerate(members):
                s = dict(spec)
                s["member"] = m
                refs.append(actor.setup.remote(s))
            ray_tpu.get(refs, timeout=120)
            self.idents = ray_tpu.get(
                [a.ident.remote() for a in members], timeout=60)
        except BaseException:
            # Partial-failure hygiene: kill whatever booted and remove
            # the PG reservation before re-raising.
            for a in members:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            if pg is not None:
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass
            raise
        self._pg = pg
        self.members = members

    def reform(self) -> Optional[int]:
        """Tear down and rebuild this stage's gang in place; restore from
        the stage's latest COMMITTED checkpoint.  Returns the restored
        version (None = nothing committed; members hold initial params)."""
        self.shutdown()
        self.incarnation += 1
        self._form()
        versions = ray_tpu.get(
            [a.load_ckpt.remote() for a in self.members], timeout=120)
        vs = {v for v in versions}
        if len(vs) != 1:
            # Members disagree (a commit raced a member's scan): converge
            # on the lowest common committed version.
            steps = ray_tpu.get(
                [a.committed_steps.remote() for a in self.members],
                timeout=60)
            common = set(steps[0]).intersection(*map(set, steps[1:])) \
                if steps else set()
            if not common:
                return None
            tgt = max(common)
            ray_tpu.get([a.load_ckpt.remote(tgt) for a in self.members],
                        timeout=120)
            return tgt
        return vs.pop()

    def beacons(self, timeout: float = 5.0) -> List[Optional[dict]]:
        """Best-effort liveness snapshot; None per member that did not
        answer (dead, or wedged past the probe timeout)."""
        refs = {a.beacon.remote(): m for m, a in enumerate(self.members)}
        out: List[Optional[dict]] = [None] * len(self.members)
        ready, _ = ray_tpu.wait(list(refs), num_returns=len(refs),
                                timeout=timeout)
        for r in ready:
            try:
                out[refs[r]] = ray_tpu.get(r)
            except Exception:
                pass
        return out

    def shutdown(self):
        for a in self.members:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self.members = []
        self.idents = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
