"""ray_tpu.train — distributed training on TPU gangs.

Reference parity: python/ray/train/ (SURVEY.md §2.3).  The execution
skeleton matches (Trainer -> BackendExecutor -> WorkerGroup of actors under
a placement group, session.report streaming); the collective fabric is
jax.distributed + XLA collectives instead of torch.distributed/NCCL.
"""

from ray_tpu.train.backend import (  # noqa: F401
    TorchBackend,
    TorchConfig,
    Backend,
    BackendConfig,
    TpuBackend,
    TpuConfig,
)
from ray_tpu.train.backend_executor import (  # noqa: F401
    BackendExecutor,
    TrainingFailedError,
)
from ray_tpu.train.data_parallel_trainer import (  # noqa: F401
    TorchTrainer,
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    Result,
)
from ray_tpu.train.session import (  # noqa: F401
    get_dataset_shard,
    get_checkpoint,
    get_context,
    get_local_rank,
    get_local_world_size,
    get_node_rank,
    get_world_rank,
    get_world_size,
    report,
)
from ray_tpu.train.worker_group import RayTrainWorker, WorkerGroup  # noqa: F401
from ray_tpu.train.pipeline_stage import (  # noqa: F401
    PipelineStageActor,
    StageGroup,
)
from ray_tpu.train.pipeline_trainer import (  # noqa: F401
    PipelineTrainer,
    jax_stage_fns,
)
