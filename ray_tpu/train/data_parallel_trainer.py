"""Trainers: BaseTrainer.fit() and the data-parallel (SPMD) trainer.

Reference parity: python/ray/train/base_trainer.py (BaseTrainer.fit:557,
Result) + data_parallel_trainer.py:56 (DataParallelTrainer,
training_loop:385).  The reference wraps fit() in a single-trial Tune run;
here fit() drives the BackendExecutor directly and the Tune integration
layers on top (tune.Tuner can wrap any Trainer via .as_trainable()).

`JaxTrainer` is the flagship entrypoint: DataParallelTrainer with the
TpuBackend — N workers, one per TPU host, fused into one jax.distributed
fabric; the user loop sees the global mesh.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import BackendConfig, TpuConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor, TrainingFailedError)


@dataclass
class Result:
    """Reference: air/result.py."""

    metrics: Optional[dict] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    metrics_dataframe: Optional[Any] = None
    metrics_history: List[dict] = field(default_factory=list)


class BaseTrainer:
    """Reference: train/base_trainer.py:557."""

    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    def training_loop(self) -> None:
        raise NotImplementedError

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapter so tune.Tuner can run this trainer as a trial."""
        trainer = self

        def trainable(config: dict):
            import copy
            t = copy.copy(trainer)
            if config:
                t = t.with_config_overrides(config)
            result = t.fit()
            if result.error is not None:
                raise result.error
            # Surface the run's final metrics (+ checkpoint) as this
            # trial's report, as the reference's trainable wrapper does.
            from ray_tpu.train import session
            session.report(result.metrics or {}, result.checkpoint)

        trainable.__name__ = type(self).__name__
        return trainable

    def with_config_overrides(self, config: dict):
        return self


class DataParallelTrainer(BaseTrainer):
    """Run `train_loop_per_worker` on every worker of the gang (SPMD).

    Reference: train/data_parallel_trainer.py:56.  Every worker must make
    the same number of session.report() calls (the same invariant the
    reference enforces; on TPU it is also the SPMD compile invariant).
    """

    _backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[dict] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config)
        self._train_loop = train_loop_per_worker
        self._train_loop_config = dict(train_loop_config or {})
        self._backend_config = backend_config or self._backend_config_cls()
        self._resume_from = resume_from_checkpoint
        self._datasets = dict(datasets or {})

    def with_config_overrides(self, config: dict):
        import copy
        t = copy.copy(self)
        merged = dict(self._train_loop_config)
        merged.update(config)
        t._train_loop_config = merged
        return t

    def fit(self) -> Result:
        executor = BackendExecutor(
            self._backend_config, self.scaling_config,
            max_failures=self.run_config.failure_config.max_failures)
        manager = self._manager = self._make_checkpoint_manager()
        executor.set_checkpoint_manager(manager)
        train_fn = self._bind_train_fn()
        history: List[dict] = []
        last_checkpoint = self._resolve_resume(manager)
        error: Optional[BaseException] = None

        executor.start()
        try:
            while True:
                # Datasets travel raw: the executor splits by the ACTUAL
                # gang size each (re)start, so an elastic resize
                # re-shards by the new world size (reference:
                # DataParallelTrainer datasets= + streaming_split).
                executor.start_training(train_fn, last_checkpoint,
                                        self._datasets)
                resized = False
                try:
                    while True:
                        # Step-boundary resize-up: returned capacity is
                        # re-admitted between reports, resuming from the
                        # latest committed step — voluntary, so it never
                        # burns the failure budget.
                        if executor.should_resize_up():
                            executor.resize_up()
                            committed = \
                                executor.latest_committed_checkpoint()
                            if committed is not None:
                                last_checkpoint = committed
                            resized = True
                            break
                        results = executor.get_next_results()
                        if results is None:
                            break
                        metrics = results[0][0]  # rank-0 metrics canonical
                        ckpts = [c for _, c in results if c is not None]
                        if ckpts:
                            last_checkpoint = ckpts[0]
                            self._persist_checkpoint(last_checkpoint,
                                                     len(history), metrics)
                        history.append(metrics)
                    if resized:
                        continue
                    executor.finish_training()
                    break
                except Exception as e:  # worker failure path
                    if isinstance(e, KeyboardInterrupt):
                        raise
                    if executor.can_restart():
                        from ray_tpu.exceptions import (
                            TrainHungError, TrainPreemptedError)

                        def _reason(err):
                            seen = set()
                            while err is not None and id(err) not in seen:
                                seen.add(id(err))
                                if isinstance(err, TrainPreemptedError):
                                    return "preempted"
                                if isinstance(err, TrainHungError):
                                    return "hang"
                                err = getattr(err, "cause", None) \
                                    or err.__cause__
                            return "failure"
                        executor.restart(reason=_reason(e))
                        # Elastic resume point: the latest COMMITTED step
                        # — an async save the dead gang never finished has
                        # no COMMIT marker and is skipped by construction.
                        committed = executor.latest_committed_checkpoint()
                        if committed is not None:
                            last_checkpoint = committed
                        continue
                    # Surface the real worker exception, not the gang
                    # wrapper around it.
                    error = e.__cause__ \
                        if (isinstance(e, TrainingFailedError)
                            and e.__cause__ is not None) else e
                    break
        finally:
            executor.shutdown()
            if manager is not None:
                try:
                    manager.wait_until_finished()
                except Exception as ckpt_err:
                    if error is None:
                        error = ckpt_err

        return Result(
            metrics=history[-1] if history else None,
            checkpoint=self._finalize_checkpoint(last_checkpoint, manager),
            error=error,
            metrics_history=history)

    def _bind_train_fn(self) -> Callable[[], None]:
        fn = self._train_loop
        cfg = dict(self._train_loop_config)
        import inspect
        takes_config = len(inspect.signature(fn).parameters) >= 1

        def bound():
            if takes_config:
                fn(cfg)
            else:
                fn()

        return bound

    def _make_checkpoint_manager(self):
        """CheckpointManager over storage_path/name (None when the run
        has no persistent storage).  CheckpointConfig maps to retention:
        num_to_keep bounds keep-best when a score attribute is set
        (reference semantics), keep-last otherwise."""
        root = self.run_config.storage_path
        if not root:
            return None
        from ray_tpu.checkpoint import CheckpointManager
        cc = self.run_config.checkpoint_config
        name = self.run_config.name or "train_run"
        if cc.checkpoint_score_attribute is not None:
            keep_last, keep_best = None, cc.num_to_keep
        else:
            keep_last, keep_best = cc.num_to_keep, None
        return CheckpointManager(
            os.path.join(root, name),
            keep_last_k=keep_last, keep_best_k=keep_best,
            best_metric=cc.checkpoint_score_attribute,
            best_mode=cc.checkpoint_score_order)

    def _resolve_resume(self, manager):
        """resume_from_checkpoint routed through the manager: "latest"
        (or "auto") resumes from the newest committed step in storage; a
        SaveHandle resolves to its directory once committed."""
        resume = self._resume_from
        from ray_tpu.checkpoint import SaveHandle
        if isinstance(resume, str):
            if resume not in ("latest", "auto"):
                raise ValueError(
                    f"resume_from_checkpoint string form must be "
                    f"'latest'/'auto', got {resume!r}")
            if manager is None:
                raise ValueError(
                    "resume_from_checkpoint='latest' requires "
                    "RunConfig(storage_path=...)")
            return manager.latest_checkpoint()
        if isinstance(resume, SaveHandle):
            return self._finalize_checkpoint(resume, manager)
        return resume

    def _persist_checkpoint(self, checkpoint, step: int,
                            metrics: Optional[dict] = None):
        """Route a reported checkpoint through the manager.  A
        SaveHandle means a worker already wrote sharded data under the
        manager root (its commit marker lands asynchronously) — only
        retention bookkeeping remains.  A dict-form Checkpoint is saved
        by the driver, asynchronously: the report loop never blocks on
        serialization or I/O."""
        manager = self._manager
        if manager is None:
            return
        from ray_tpu.checkpoint import SaveHandle
        if isinstance(checkpoint, SaveHandle):
            manager.track(checkpoint.step if checkpoint.step is not None
                          else step, metrics)
        elif isinstance(checkpoint, Checkpoint) and checkpoint.is_sharded:
            manager.track(step, metrics)
        else:
            manager.save(step, checkpoint.to_dict(), metrics=metrics)

    def _finalize_checkpoint(self, checkpoint, manager):
        """Result.checkpoint must be restorable by the caller: resolve a
        SaveHandle to its committed directory (worker-side handles are
        polled through the COMMIT marker on the shared filesystem)."""
        from ray_tpu.checkpoint import SaveHandle
        if not isinstance(checkpoint, SaveHandle):
            return checkpoint
        deadline = time.monotonic() + 60.0
        while not checkpoint.committed() and time.monotonic() < deadline:
            time.sleep(0.05)
        if checkpoint.committed():
            return Checkpoint.from_sharded_dir(checkpoint.directory)
        # Never committed (writer died): fall back to the newest step
        # that did.
        return manager.latest_checkpoint() if manager is not None else None


class TorchTrainer(DataParallelTrainer):
    """Data-parallel torch training over a real torch.distributed process
    group (reference: train/torch/torch_trainer.py:15 — workers are
    actors; the gradient allreduce is torch's own gloo/nccl collective,
    the framework stays out of the data path)."""

    def __init__(self, train_loop_per_worker, *, torch_config=None,
                 **kwargs):
        from ray_tpu.train.backend import TorchConfig
        self._backend_config_cls = TorchConfig
        super().__init__(train_loop_per_worker,
                         backend_config=torch_config or TorchConfig(),
                         **kwargs)


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer wired to the jax.distributed TPU backend
    (the TorchTrainer/NCCL analogue — reference train/torch/torch_trainer.py
    :15 — with the fabric swapped for ICI + XLA collectives)."""

    _backend_config_cls = TpuConfig

    def __init__(self, train_loop_per_worker: Callable,
                 *, jax_config: Optional[TpuConfig] = None, **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=jax_config or TpuConfig(), **kwargs)
