"""WorkerGroup: a gang of training-worker actors under one placement group.

Reference parity: python/ray/train/_internal/worker_group.py — WorkerGroup:92
(execute/execute_async over a fleet of RayTrainWorker:17 actors).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import ray_tpu
from ray_tpu.util.placement_group import (
    PlacementGroup, placement_group, remove_placement_group)


@ray_tpu.remote
class RayTrainWorker:
    """One training worker process (reference: worker_group.py:17).  Holds
    the per-worker _TrainSession; generic `run` executes arbitrary fns so
    backends can do env setup / rendezvous on the worker."""

    def run(self, fn, *args, **kwargs):
        return fn(*args, **kwargs)

    def set_env(self, env: dict):
        import os
        os.environ.update({k: str(v) for k, v in env.items()})
        return True

    def init_session(self, train_fn, context, checkpoint=None,
                     dataset_shards=None):
        from ray_tpu.train import session as session_mod
        sess = session_mod._TrainSession(train_fn, context, checkpoint,
                                         dataset_shards)
        session_mod._session = sess
        self._session = sess
        sess.start()
        return True

    def get_next(self, timeout: float | None = None):
        return self._session.get_next(timeout)

    def beacon(self):
        """Progress snapshot for the driver hang watchdog.  Runs on a
        concurrent actor thread (max_concurrency > 1) so it answers even
        while get_next blocks in the result queue."""
        sess = getattr(self, "_session", None)
        return sess.beacon() if sess is not None else None

    def stop_session(self):
        """Ask the session's user thread to exit at its next report —
        the cooperative teardown a resize uses before re-forming."""
        sess = getattr(self, "_session", None)
        if sess is not None:
            sess.stop()
        return True

    def finish_session(self):
        self._session.finish()
        return True

    def node_id(self):
        import os
        return os.environ.get("RAY_TPU_NODE_ID", "")

    def pid(self):
        import os
        return os.getpid()


@dataclass
class Worker:
    actor: Any
    rank: int
    node_id: str = ""
    pid: int = 0


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: dict,
                 placement_strategy: str = "PACK",
                 pg_timeout_s: float = 120.0):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self._pg: Optional[PlacementGroup] = placement_group(
            [dict(resources_per_worker) for _ in range(num_workers)],
            strategy=placement_strategy)
        self.workers: List[Worker] = []
        # Everything after the PG is created runs under the cleanup
        # umbrella: a raising pg.wait() (GCS hiccup, interrupt) or a
        # failure anywhere in actor construction must remove the
        # just-reserved bundles, or repeated elastic restarts leak PG
        # reservations until the cluster can't place anything.
        try:
            if not self._pg.wait(pg_timeout_s):
                raise RuntimeError(
                    f"could not reserve {num_workers} x "
                    f"{resources_per_worker} (strategy "
                    f"{placement_strategy}) within {pg_timeout_s:g}s")
            res = dict(resources_per_worker)
            cpu = res.pop("CPU", 0)
            tpu = res.pop("TPU", None)
            # max_concurrency: beacon() must answer on a second actor
            # thread while get_next blocks in the result queue.
            actor_cls = RayTrainWorker.options(
                num_cpus=cpu, num_tpus=tpu, resources=res or None,
                max_concurrency=4)
            for rank in range(num_workers):
                actor = actor_cls.options(
                    placement_group=self._pg,
                    placement_group_bundle_index=rank).remote()
                self.workers.append(Worker(actor=actor, rank=rank))
            # Resolve worker placement (node ids + pids): local-rank
            # assignment and the watchdog's per-node stack collection.
            node_ids = ray_tpu.get(
                [w.actor.node_id.remote() for w in self.workers], timeout=120)
            pids = ray_tpu.get(
                [w.actor.pid.remote() for w in self.workers], timeout=120)
            for w, nid, pid in zip(self.workers, node_ids, pids):
                w.node_id = nid
                w.pid = pid
        except BaseException:
            # Don't leak the gang's reserved bundles if construction
            # fails partway — including the wait-timeout/raise paths.
            self.shutdown()
            raise

    def __len__(self):
        return len(self.workers)

    def execute_async(self, fn: Callable, *args, **kwargs) -> list:
        return [w.actor.run.remote(fn, *args, **kwargs)
                for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> list:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].actor.run.remote(fn, *args, **kwargs))

    def local_ranks(self) -> list[tuple[int, int]]:
        """(local_rank, local_world_size) per worker, grouped by node."""
        by_node: dict[str, int] = {}
        counts: dict[str, int] = {}
        for w in self.workers:
            counts[w.node_id] = counts.get(w.node_id, 0) + 1
        out = []
        for w in self.workers:
            lr = by_node.get(w.node_id, 0)
            by_node[w.node_id] = lr + 1
            out.append((lr, counts[w.node_id]))
        return out

    def node_ranks(self) -> list[int]:
        order: dict[str, int] = {}
        out = []
        for w in self.workers:
            if w.node_id not in order:
                order[w.node_id] = len(order)
            out.append(order[w.node_id])
        return out

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers.clear()
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
