"""Training backends: how a worker gang becomes one SPMD compute fabric.

Reference parity: python/ray/train/backend.py (Backend/BackendConfig) +
torch/config.py:155 _TorchBackend (rank-0 TCP rendezvous ->
dist.init_process_group(nccl), :69-:113).

TPU-native design: the collective fabric is jax.distributed — worker 0
publishes a coordinator address, every worker calls
`jax.distributed.initialize(coordinator, num_processes, process_id)`, and
from then on `jax.devices()` spans the whole gang and XLA compiles
collectives onto ICI/DCN.  No NCCL, no process groups: the mesh IS the
communicator.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks around the training lifecycle (reference: train/backend.py)."""

    def on_start(self, worker_group: WorkerGroup, config: BackendConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup, config: BackendConfig):
        pass

    def on_training_start(self, worker_group: WorkerGroup,
                          config: BackendConfig):
        pass


# ------------------------- TPU / JAX backend -------------------------------


@dataclass
class TpuConfig(BackendConfig):
    """Configuration for the jax.distributed fabric.

    env_per_worker: extra env vars set on every worker BEFORE jax imports
    (e.g. {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "--xla_force_host_platform_
    device_count=2"} to simulate a 2-chip host per worker in tests).
    """

    env_per_worker: dict = field(default_factory=dict)
    coordinator_port: Optional[int] = None
    init_timeout_s: float = 120.0

    def backend_cls(self):
        return TpuBackend


def _find_free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _coordinator_host() -> str:
    import socket
    return socket.gethostbyname(socket.gethostname())


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int, env: dict):
    os.environ.update({k: str(v) for k, v in env.items()})
    import jax

    if "JAX_PLATFORMS" in env:
        try:
            jax.config.update("jax_platforms", env["JAX_PLATFORMS"])
        except Exception:
            pass
    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    return {"process_id": process_id,
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices())}


def _shutdown_jax_distributed():
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    return True


class TpuBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, config: TpuConfig):
        port = config.coordinator_port or worker_group.execute_single(
            0, _find_free_port)
        host = worker_group.execute_single(0, _coordinator_host)
        coordinator = f"{host}:{port}"
        n = len(worker_group)
        refs = []
        for rank, worker in enumerate(worker_group.workers):
            refs.append(worker.actor.run.remote(
                _init_jax_distributed, coordinator, n, rank,
                dict(config.env_per_worker)))
        import ray_tpu
        infos = ray_tpu.get(refs, timeout=config.init_timeout_s)
        devices = {i["global_devices"] for i in infos}
        if len(devices) != 1:
            raise RuntimeError(
                f"inconsistent global device view across workers: {infos}")

    def on_shutdown(self, worker_group: WorkerGroup, config: TpuConfig):
        try:
            worker_group.execute(_shutdown_jax_distributed)
        except Exception:
            pass


# ------------------------- Torch backend -----------------------------------


@dataclass
class TorchConfig(BackendConfig):
    """torch.distributed process-group fabric (reference:
    train/torch/config.py:155 _TorchBackend; :69 _setup_torch_process_group
    -> dist.init_process_group:113).  Backend "gloo" (CPU; this image ships
    CPU torch — on CUDA hosts "nccl" slots in unchanged)."""

    backend: str = "gloo"
    init_timeout_s: float = 120.0

    def backend_cls(self):
        return TorchBackend


def _init_torch_process_group(master_addr: str, master_port: int,
                              backend: str, rank: int, world_size: int,
                              timeout_s: float):
    import datetime

    import torch.distributed as dist
    if dist.is_initialized():
        dist.destroy_process_group()
    dist.init_process_group(
        backend=backend,
        init_method=f"tcp://{master_addr}:{master_port}",
        rank=rank, world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))
    return {"rank": dist.get_rank(), "world_size": dist.get_world_size()}


def _shutdown_torch_process_group():
    import torch.distributed as dist
    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, config: TorchConfig):
        port = worker_group.execute_single(0, _find_free_port)
        host = worker_group.execute_single(0, _coordinator_host)
        n = len(worker_group)
        import ray_tpu
        refs = [worker.actor.run.remote(
                    _init_torch_process_group, host, port, config.backend,
                    rank, n, config.init_timeout_s)
                for rank, worker in enumerate(worker_group.workers)]
        infos = ray_tpu.get(refs, timeout=config.init_timeout_s)
        if any(i["world_size"] != n for i in infos):
            raise RuntimeError(f"torch process group mismatch: {infos}")

    def on_shutdown(self, worker_group: WorkerGroup, config: TorchConfig):
        try:
            worker_group.execute(_shutdown_torch_process_group)
        except Exception:
            pass
