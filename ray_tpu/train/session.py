"""Worker-side training session.

Reference parity: python/ray/train/_internal/session.py — _TrainSession:63
(user fn in a thread, result_queue(1)/error_queue :119-125, report:322,
checkpoint:284) and python/ray/air/session.py (the public accessors).

The user's train loop runs in a thread on the worker actor; `report()`
blocks the loop on a depth-1 queue until the driver consumes the result —
natural backpressure, exactly the reference's design.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.exceptions import TrainPreemptedError

_session: Optional["_TrainSession"] = None

_STEP_MET = None


def _step_metrics():
    global _STEP_MET
    if _STEP_MET is None:
        from ray_tpu.util import metrics as mt
        _STEP_MET = {
            "step_time": mt.Histogram(
                "train_step_time_s",
                "wall seconds between report() step boundaries",
                tag_keys=("rank",),
                buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                         0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                         120.0, 300.0)),
        }
    return _STEP_MET


@dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str = ""
    trial_name: str = ""
    # Sharded-checkpoint plumbing: where this run's CheckpointManager
    # lives (empty = no persistent storage configured) and which elastic
    # incarnation this worker belongs to (bumped per restart; save_id
    # fodder so a new gang never aliases a dead gang's torn save).
    checkpoint_root: str = ""
    restart_count: int = 0


class _TrainSession:
    def __init__(self, train_fn: Callable[[], Any], context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[dict] = None):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.result_queue: queue.Queue = queue.Queue(maxsize=1)
        self.continue_event = threading.Event()
        self.error: Optional[BaseException] = None
        self.finished = False
        self._stop = False
        # Progress beacon: step counter + wall time of the last completed
        # step boundary, polled by the driver watchdog through the actor's
        # concurrent beacon() method while get_next blocks.
        self._beacon_step = 0
        self._beacon_t = time.monotonic()
        # Preemption notice state: armed by the hostd fan-out (via the
        # CoreWorker PreemptionNotice RPC); consumed at the next report()
        # step boundary — run the grace-window save hook, then abort with
        # TrainPreemptedError so at most the in-flight step is lost.
        self._preempt_pending = False
        self._preempt_deadline: Optional[float] = None
        self._preempt_grace = 0.0
        self._preempt_hook: Optional[Callable[[float], Any]] = None
        # Interruptible chaos stall (hang injection for the watchdog).
        self._stall_abort = threading.Event()
        # Open train/step span between report() boundaries (always on:
        # step cadence is orders of magnitude below the ring's budget).
        self._step_span = None

        def run():
            global _session
            _session = self
            try:
                train_fn()
            except StopIteration:
                pass
            except BaseException as e:  # noqa: BLE001
                self.error = e
            finally:
                from ray_tpu.util import spans
                spans.end(self._step_span, final=True)
                self._step_span = None
                # Sentinel BEFORE the finished flag: a concurrent get_next
                # must never see finished+empty while an error is pending.
                try:
                    self.result_queue.put(("__done__", None), timeout=0)
                except queue.Full:
                    pass
                self.finished = True

        self.thread = threading.Thread(target=run, daemon=True)

    def start(self):
        self.thread.start()

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        if self._stop:
            raise StopIteration  # unblocks and ends the user loop
        # Chaos stall BEFORE the beacon update: the stalled rank's beacon
        # stays at the previous step, so the driver watchdog classifies
        # it as the laggard.  Interruptible via stop().
        from ray_tpu._private.fault_injection import get_chaos
        chaos = get_chaos()
        if chaos is not None:
            stall = chaos.stall_train_step()
            if stall:
                from ray_tpu.util import events
                events.record("train", "chaos_stall", stall_s=stall,
                              rank=self.context.world_rank)
                self._stall_abort.wait(stall)
                if self._stop:
                    raise StopIteration
        prev_t = self._beacon_t
        self._beacon_step += 1
        self._beacon_t = time.monotonic()
        from ray_tpu.util import events, spans
        events.record("train", "beacon", step=self._beacon_step,
                      rank=self.context.world_rank)
        # Durational step span: one per inter-report gap (the span for
        # step N opens at report N-1 and closes here).
        spans.end(self._step_span)
        self._step_span = spans.begin(
            "train", "step", step=self._beacon_step + 1,
            rank=self.context.world_rank)
        if self._beacon_step > 1:
            # Wall time between step boundaries — the worker-side
            # train_step_time_s SLO histogram (first report excluded: it
            # measures setup, not a step).
            _step_metrics()["step_time"].observe(
                self._beacon_t - prev_t,
                tags={"rank": str(self.context.world_rank)})
        if self._preempt_pending:
            # Step boundary after a preemption notice: run the proactive
            # save hook with whatever is left of the grace window, then
            # abort — resuming from this save loses at most the step that
            # was in flight when the notice landed.
            self._preempt_pending = False
            remaining = self._preempt_grace
            if self._preempt_deadline is not None:
                remaining = max(0.0,
                                self._preempt_deadline - time.monotonic())
            if self._preempt_hook is not None:
                try:
                    self._preempt_hook(remaining)
                except Exception:
                    pass  # a failed rescue save must not mask the abort
            events.record("train", "preempt_abort",
                          rank=self.context.world_rank,
                          step=self._beacon_step,
                          grace_remaining_s=round(remaining, 3))
            raise TrainPreemptedError(self._preempt_grace,
                                      self.context.world_rank)
        self.result_queue.put((metrics, checkpoint))  # blocks when full
        self.continue_event.wait()
        self.continue_event.clear()
        if self._stop:
            raise StopIteration

    def notify_preemption(self, grace_s: float) -> None:
        """Arm the step-boundary abort (called from the CoreWorker
        PreemptionNotice RPC thread)."""
        from ray_tpu.util import events
        events.record("train", "preempt_notice", grace_s=float(grace_s),
                      rank=self.context.world_rank)
        self._preempt_grace = float(grace_s)
        self._preempt_deadline = time.monotonic() + float(grace_s)
        self._preempt_pending = True

    def beacon(self) -> dict:
        """Progress snapshot for the driver watchdog (served through a
        concurrent actor method while get_next blocks)."""
        return {"step": self._beacon_step,
                "age_s": time.monotonic() - self._beacon_t,
                "finished": self.finished}

    def get_next(self, timeout: float | None = None):
        """Driver side (via actor RPC): next report, or None when done.
        Blocks indefinitely by default — worker DEATH surfaces as an RPC
        failure to the caller, not as a queue timeout, so a long-running
        train step must not be mistaken for a failure."""
        if self.finished and self.result_queue.empty():
            if self.error is not None:
                raise self.error
            return None
        item = self.result_queue.get(timeout=timeout)
        if item == ("__done__", None):
            if self.error is not None:
                raise self.error
            return None
        self.continue_event.set()
        return item

    def finish(self, timeout: float = 60.0):
        self.thread.join(timeout)
        # Drain this worker's async checkpoint writer: training is not
        # "finished" while its last save could still be torn.
        mgr = getattr(self, "_ckpt_manager", None)
        if mgr is not None:
            mgr.wait_until_finished()
        if self.error is not None:
            raise self.error

    def stop(self):
        self._stop = True
        self.continue_event.set()
        self._stall_abort.set()  # wake an injected stall so teardown works


def get_session() -> "_TrainSession":
    if _session is None:
        raise RuntimeError(
            "No training session active — this API must be called inside a "
            "train_loop_per_worker launched by a Trainer")
    return _session


# ---------------------------------------------------------------------------
# Public session API (reference: ray.air.session / ray.train.*)
# ---------------------------------------------------------------------------


def report(metrics: dict, checkpoint=None) -> None:
    """Stream one step's metrics (and optionally a checkpoint) to the
    driver.  `checkpoint` may be an air.Checkpoint OR an async
    ray_tpu.checkpoint.SaveHandle — a handle crosses to the driver as a
    lightweight (directory, step) ticket, so reporting never blocks on
    checkpoint serialization or I/O."""
    get_session().report(dict(metrics), checkpoint)


def get_checkpoint_manager():
    """This worker's CheckpointManager over the run's storage root
    (requires RunConfig.storage_path on the trainer).  Its save_id is
    derived from the elastic restart count, so saves from a restarted
    gang never alias a dead gang's torn directories."""
    sess = get_session()
    mgr = getattr(sess, "_ckpt_manager", None)
    if mgr is None:
        root = sess.context.checkpoint_root
        if not root:
            raise RuntimeError(
                "no checkpoint storage configured — pass "
                "RunConfig(storage_path=...) to the trainer to use "
                "sharded checkpointing")
        from ray_tpu.checkpoint import CheckpointManager
        mgr = CheckpointManager(
            root, save_id=f"i{sess.context.restart_count}")
        sess._ckpt_manager = mgr
    return mgr


def get_dataset_shard(name: str = "train"):
    """This worker's streaming shard of a trainer dataset (reference:
    air/session.py get_dataset_shard backed by streaming_split)."""
    shard = get_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset shard {name!r}; pass datasets={{{name!r}: ds}} to "
            f"the trainer")
    return shard


def iter_device_batches(name: str = "train", *, sharding=None, **kwargs):
    """Overlapped device feed over this worker's dataset shard —
    shorthand for ``get_dataset_shard(name).iter_device_batches(...)``.
    Yields batches already on the accelerator (double-buffered H2D: batch
    k+1 transfers while the step consumes batch k); pass ``sharding=``
    a NamedSharding, a Mesh, or a dict column -> Sharding to land each
    batch pre-sharded for the jitted step."""
    return get_dataset_shard(name).iter_device_batches(
        sharding=sharding, **kwargs)


def set_preemption_hook(fn: Callable[[float], Any]) -> None:
    """Register the grace-window rescue: on a preemption notice, `fn`
    runs at the next step boundary with the REMAINING grace seconds and
    should save a checkpoint (typically
    ``get_checkpoint_manager().save(state, step).wait()``).  report()
    then aborts the loop with TrainPreemptedError, so an elastic restart
    resumes from this save having lost at most the in-flight step."""
    get_session()._preempt_hook = fn


def preemption_deadline() -> Optional[float]:
    """Seconds until this host is reclaimed, or None if no preemption
    notice is pending — lets a train loop skip non-essential work (eval,
    logging) when the clock is running."""
    sess = get_session()
    if sess._preempt_deadline is None:
        return None
    return max(0.0, sess._preempt_deadline - time.monotonic())


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().loaded_checkpoint


def get_context() -> TrainContext:
    return get_session().context


def get_world_rank() -> int:
    return get_session().context.world_rank


def get_world_size() -> int:
    return get_session().context.world_size


def get_local_rank() -> int:
    return get_session().context.local_rank


def get_local_world_size() -> int:
    return get_session().context.local_world_size


def get_node_rank() -> int:
    return get_session().context.node_rank
