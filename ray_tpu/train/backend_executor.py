"""BackendExecutor: owns the worker gang and drives the training lifecycle.

Reference parity: python/ray/train/_internal/backend_executor.py —
BackendExecutor:43 (start:94 creates PG + WorkerGroup, start_training:325,
get_with_failure_handling:522, _restart:583 elastic restart).
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()()
        self._scaling = scaling_config
        self._max_failures = max_failures
        self._num_failures = 0
        self.worker_group: Optional[WorkerGroup] = None
        # Optional ray_tpu.checkpoint.CheckpointManager over the run's
        # storage root: workers learn its root through TrainContext, and
        # elastic restart resumes from its latest COMMITTED step.
        self.checkpoint_manager = None

    def set_checkpoint_manager(self, manager) -> None:
        self.checkpoint_manager = manager

    def start(self):
        self.worker_group = WorkerGroup(
            self._scaling.num_workers,
            self._scaling.worker_resources(),
            self._scaling.placement_strategy)
        self._backend.on_start(self.worker_group, self._backend_config)

    def start_training(self, train_fn: Callable[[], None],
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards: Optional[dict] = None):
        wg = self.worker_group
        self._backend.on_training_start(wg, self._backend_config)
        local = wg.local_ranks()
        node_ranks = wg.node_ranks()
        refs = []
        ckpt_root = (self.checkpoint_manager.root
                     if self.checkpoint_manager is not None else "")
        for rank, worker in enumerate(wg.workers):
            ctx = TrainContext(
                world_rank=rank,
                world_size=len(wg),
                local_rank=local[rank][0],
                local_world_size=local[rank][1],
                node_rank=node_ranks[rank],
                checkpoint_root=ckpt_root,
                restart_count=self._num_failures)
            per_worker = {name: shards[rank] for name, shards
                          in (dataset_shards or {}).items()}
            refs.append(worker.actor.init_session.remote(
                train_fn, ctx, checkpoint, per_worker))
        ray_tpu.get(refs, timeout=120)

    # How long some workers may keep reporting after others finished before
    # the SPMD-mismatch diagnostic fires (a finished worker never reports
    # again, so this only delays an error, never a success).
    MISMATCH_GRACE_S = 60.0

    def get_next_results(self) -> Optional[List]:
        """One report from EVERY worker, or None when all finished.
        A dead worker surfaces as an RPC error (the caller decides on
        restart); a worker that FINISHES while peers still report trips the
        SPMD-mismatch diagnostic instead of hanging forever in a collective."""
        import time as _time

        wg = self.worker_group
        refs = [w.actor.get_next.remote(None) for w in wg.workers]
        results: List = [None] * len(refs)
        pending = {ref: i for i, ref in enumerate(refs)}
        got: set = set()
        first_done_at = None
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=5.0)
            for r in ready:
                i = pending.pop(r)
                results[i] = ray_tpu.get(r)
                got.add(i)
            finished = [i for i in got if results[i] is None]
            if finished and first_done_at is None:
                first_done_at = _time.monotonic()
            if finished and pending and first_done_at is not None \
                    and _time.monotonic() - first_done_at \
                    > self.MISMATCH_GRACE_S:
                raise TrainingFailedError(
                    "some workers finished while others are still "
                    "reporting — the train loop must be SPMD (same number "
                    "of report() calls on every worker)")
        if all(r is None for r in results):
            return None
        if any(r is None for r in results):
            raise TrainingFailedError(
                "some workers finished while others are still reporting — "
                "the train loop must be SPMD (same number of report() "
                "calls on every worker)")
        return results

    def finish_training(self):
        wg = self.worker_group
        ray_tpu.get([w.actor.finish_session.remote() for w in wg.workers],
                    timeout=120)

    def can_restart(self) -> bool:
        return (self._max_failures == -1
                or self._num_failures < self._max_failures)

    def latest_committed_checkpoint(self) -> Optional[Checkpoint]:
        """The newest COMMITTED step under the checkpoint manager, as a
        Checkpoint — what an elastic restart resumes from.  An async
        save the dead gang never committed is invisible here by
        construction (no COMMIT marker), so a restart can never resume
        from a torn checkpoint."""
        mgr = self.checkpoint_manager
        if mgr is None:
            return None
        try:
            mgr.wait_until_finished()   # drain any driver-side writer
        except Exception as e:
            logger.warning("async checkpoint write failed: %s", e)
        step = mgr.latest_step()
        if step is None:
            return None
        return Checkpoint.from_sharded_dir(mgr.step_dir(step))

    def restart(self):
        """Elastic restart: tear the gang down, rebuild, re-rendezvous
        (reference: backend_executor.py:583).  On TPU a lost host means the
        slice re-forms as a whole — per-worker restart is not a thing."""
        self._num_failures += 1
        logger.warning("restarting worker group (failure %d/%s)",
                       self._num_failures, self._max_failures)
        self.shutdown()
        self.start()

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
