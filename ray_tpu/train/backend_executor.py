"""BackendExecutor: owns the worker gang and drives the training lifecycle.

Reference parity: python/ray/train/_internal/backend_executor.py —
BackendExecutor:43 (start:94 creates PG + WorkerGroup, start_training:325,
get_with_failure_handling:522, _restart:583 elastic restart).

On top of the reference lifecycle this executor carries the train-plane
fault-tolerance layer:

- **Hang watchdog** — while blocked waiting for gang reports it polls
  per-worker progress beacons (served on a concurrent actor thread); no
  observable progress for ``train_hang_timeout_s`` converts the infinite
  collective wait into `TrainHungError` carrying the laggard ranks, their
  beacon ages, and live per-rank thread stacks collected through the
  hostd CollectStacks RPC.
- **Elastic gang formation** — with ``ScalingConfig.min_workers`` set,
  `restart()` re-forms on the surviving hosts (fewer workers, data
  re-sharded by the new world size) instead of waiting for a lost host's
  replacement, and `resize_up()` re-admits returned capacity at a step
  boundary.  Each (re)formation bumps a generation counter that feeds
  `TrainContext.restart_count`, so checkpoint save_ids never alias
  across gang incarnations.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.exceptions import TrainHungError
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger("ray_tpu.train")


def _cfg():
    from ray_tpu._private.config import GLOBAL_CONFIG
    return GLOBAL_CONFIG


_M = None


def _metrics():
    """Train-plane recovery metrics (exported via util.metrics like every
    other plane; `cli metrics` scrapes them from the driver)."""
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "train_recoveries": mt.Counter(
                "train_recoveries",
                "gang restarts/resizes, tagged by reason"),
            "train_recovery_seconds": mt.Histogram(
                "train_recovery_seconds",
                "wall seconds from failure to a re-formed gang"),
            "train_hangs": mt.Counter(
                "train_hangs", "gangs declared hung by the watchdog"),
        }
    return _M


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()()
        self._scaling = scaling_config
        self._max_failures = max_failures
        self._num_failures = 0
        # Gang incarnation: bumped on EVERY re-formation (failure restart
        # or resize-up) — feeds TrainContext.restart_count so a new
        # gang's checkpoint save_id never aliases a dead gang's torn
        # markers.  _num_failures stays the can_restart budget only.
        self._generation = 0
        self._last_resize_check = 0.0
        # First monotonic time the resize-up capacity probe saw room to
        # grow (None = not seen).  A single sighting is not trusted: the
        # GCS resource view lags hostd state by a heartbeat, so right
        # after a gang forms its own freshly-reserved bundles can still
        # read as free capacity — acting on that tears the gang down in
        # a resize loop.  Growth requires the surplus to persist across
        # two probes spaced at least two heartbeats apart.
        self._resize_ready_since: Optional[float] = None
        self.worker_group: Optional[WorkerGroup] = None
        # Optional ray_tpu.checkpoint.CheckpointManager over the run's
        # storage root: workers learn its root through TrainContext, and
        # elastic restart resumes from its latest COMMITTED step.
        self.checkpoint_manager = None

    def set_checkpoint_manager(self, manager) -> None:
        self.checkpoint_manager = manager

    # ---------------- gang formation ----------------

    def start(self):
        self.worker_group = self._form_gang()
        self._backend.on_start(self.worker_group, self._backend_config)
        # Fresh gang: restart the capacity-probe debounce so a stale
        # pre-formation resource view can't immediately trigger a resize.
        self._last_resize_check = time.monotonic()
        self._resize_ready_since = None

    def _form_gang(self) -> WorkerGroup:
        """Reserve and boot a gang.  Without min_workers this is the
        legacy exact-size path.  With it, try the full size first, then
        walk down to min_workers (resize-down onto survivors), retrying
        under train_elastic_timeout_s — a lost host shrinks the gang
        instead of stalling the restart until a replacement appears."""
        s = self._scaling
        min_w = getattr(s, "min_workers", None)
        if min_w is None:
            return WorkerGroup(s.num_workers, s.worker_resources(),
                               s.placement_strategy)
        min_w = max(1, min(int(min_w), s.num_workers))
        deadline = time.monotonic() + _cfg().train_elastic_timeout_s
        attempt_s = _cfg().train_pg_timeout_s
        last_err: Optional[BaseException] = None
        while True:
            for n in range(s.num_workers, min_w - 1, -1):
                budget = deadline - time.monotonic()
                if budget <= 0:
                    break
                try:
                    wg = WorkerGroup(
                        n, s.worker_resources(), s.placement_strategy,
                        pg_timeout_s=min(attempt_s, max(1.0, budget)))
                    if n < s.num_workers:
                        logger.warning(
                            "elastic start: formed %d/%d workers "
                            "(resize-down onto survivors)",
                            n, s.num_workers)
                    return wg
                except Exception as e:  # noqa: BLE001
                    last_err = e
            if time.monotonic() >= deadline:
                raise TrainingFailedError(
                    f"could not form an elastic gang of "
                    f"{min_w}..{s.num_workers} workers within "
                    f"{_cfg().train_elastic_timeout_s:g}s"
                ) from last_err

    def start_training(self, train_fn: Callable[[], None],
                       checkpoint: Optional[Checkpoint] = None,
                       datasets: Optional[dict] = None):
        """Launch the user loop on every worker of the CURRENT gang.
        Datasets are split here, by the actual gang size — an elastic
        restart that re-formed smaller re-shards by the new world size
        instead of leaving shards orphaned on dead ranks."""
        wg = self.worker_group
        self._backend.on_training_start(wg, self._backend_config)
        # ingest_work_stealing=True swaps the static per-worker lists for
        # SplitCoordinator leases (straggler-proof; re-split per (re)start
        # so gang resizes recreate the coordinator).  The static split
        # stays the default: it is deterministic, which token-exact
        # elastic restores rely on.
        steal = _cfg().ingest_work_stealing
        dataset_shards = {
            name: ds.streaming_split(len(wg), equal=True, steal=steal)
            for name, ds in (datasets or {}).items()}
        local = wg.local_ranks()
        node_ranks = wg.node_ranks()
        refs = []
        ckpt_root = (self.checkpoint_manager.root
                     if self.checkpoint_manager is not None else "")
        for rank, worker in enumerate(wg.workers):
            ctx = TrainContext(
                world_rank=rank,
                world_size=len(wg),
                local_rank=local[rank][0],
                local_world_size=local[rank][1],
                node_rank=node_ranks[rank],
                checkpoint_root=ckpt_root,
                restart_count=self._generation)
            per_worker = {name: shards[rank] for name, shards
                          in dataset_shards.items()}
            refs.append(worker.actor.init_session.remote(
                train_fn, ctx, checkpoint, per_worker))
        ray_tpu.get(refs, timeout=120)

    # How long some workers may keep reporting after others finished before
    # the SPMD-mismatch diagnostic fires (a finished worker never reports
    # again, so this only delays an error, never a success).
    MISMATCH_GRACE_S = 60.0

    # ---------------- report pump + hang watchdog ----------------

    def get_next_results(self) -> Optional[List]:
        """One report from EVERY worker, or None when all finished.
        A dead worker surfaces as an RPC error (the caller decides on
        restart); a worker that FINISHES while peers still report trips the
        SPMD-mismatch diagnostic instead of hanging forever in a collective.

        While blocked, the hang watchdog polls per-worker step beacons:
        progress is a ready report OR any beacon-step advance; a stall
        past train_hang_timeout_s raises TrainHungError naming the
        laggard ranks with their live thread stacks."""
        wg = self.worker_group
        refs = [w.actor.get_next.remote(None) for w in wg.workers]
        results: List = [None] * len(refs)
        pending = {ref: i for i, ref in enumerate(refs)}
        got: set = set()
        first_done_at = None
        hang_timeout = _cfg().train_hang_timeout_s
        poll_s = max(0.1, _cfg().train_beacon_poll_s)
        last_progress = time.monotonic()
        last_beacons: Dict[int, dict] = {}
        last_poll = 0.0
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=min(5.0, poll_s))
            now = time.monotonic()
            if ready:
                last_progress = now
            for r in ready:
                i = pending.pop(r)
                results[i] = ray_tpu.get(r)
                got.add(i)
            if pending and not ready and now - last_poll >= poll_s:
                last_poll = now
                beacons = self._poll_beacons(sorted(pending.values()))
                for rank, b in beacons.items():
                    prev = last_beacons.get(rank)
                    if prev is not None and b["step"] > prev["step"]:
                        last_progress = now  # a rank moved: not hung
                    last_beacons[rank] = b
            if pending and now - last_progress > hang_timeout:
                self._raise_hung(sorted(pending.values()), last_beacons,
                                 hang_timeout)
            finished = [i for i in got if results[i] is None]
            if finished and first_done_at is None:
                first_done_at = time.monotonic()
            if finished and pending and first_done_at is not None \
                    and time.monotonic() - first_done_at \
                    > self.MISMATCH_GRACE_S:
                raise TrainingFailedError(
                    self._mismatch_message(sorted(pending.values()),
                                           last_beacons))
        if all(r is None for r in results):
            return None
        if any(r is None for r in results):
            laggards = [i for i, r in enumerate(results) if r is not None]
            raise TrainingFailedError(
                self._mismatch_message(laggards, last_beacons))
        return results

    def _poll_beacons(self, ranks: List[int]) -> Dict[int, dict]:
        """Best-effort beacon snapshot from the given ranks (concurrent
        actor method: answers even while get_next blocks)."""
        wg = self.worker_group
        refs = {wg.workers[r].actor.beacon.remote(): r for r in ranks}
        out: Dict[int, dict] = {}
        ready, _ = ray_tpu.wait(list(refs), num_returns=len(refs),
                                timeout=2.0)
        for ref in ready:
            try:
                b = ray_tpu.get(ref)
            except Exception:
                continue  # dead worker: its get_next ref carries the error
            if b is not None:
                out[refs[ref]] = b
        return out

    def _mismatch_message(self, laggard_ranks: List[int],
                          beacons: Dict[int, dict]) -> str:
        ages = ", ".join(
            f"rank {r}: "
            + (f"{beacons[r]['age_s']:.1f}s ago (step "
               f"{beacons[r]['step']})" if r in beacons else "unknown")
            for r in laggard_ranks)
        return (
            "some workers finished while others are still reporting — the "
            "train loop must be SPMD (same number of report() calls on "
            f"every worker); laggard rank(s) {laggard_ranks} "
            f"(last beacon: {ages})")

    def _raise_hung(self, pending_ranks: List[int],
                    beacons: Dict[int, dict], timeout_s: float):
        """Diagnose and raise: laggards are the pending ranks at the
        LOWEST beacon step (healthy ranks also look stale while blocked
        on the driver, but they sit at the gang's furthest step)."""
        fresh = self._poll_beacons(pending_ranks)
        beacons = dict(beacons)
        beacons.update(fresh)
        steps = {r: beacons[r]["step"] for r in pending_ranks
                 if r in beacons}
        if steps:
            lowest = min(steps.values())
            laggards = sorted(r for r, s in steps.items() if s == lowest)
        else:
            laggards = list(pending_ranks)  # no beacons at all
        ages = {r: beacons[r]["age_s"] for r in laggards if r in beacons}
        stacks = self._collect_stacks(laggards)
        _metrics()["train_hangs"].inc()
        from ray_tpu.util import events
        events.record("train", "hang", laggards=laggards,
                      timeout_s=timeout_s)
        raise TrainHungError(timeout_s, laggards, ages, stacks)

    def _collect_stacks(self, ranks: List[int]) -> str:
        """Live thread dumps for the given ranks via each node's hostd
        CollectStacks RPC (per-node fan-out; inside each node the hostd
        probes its workers concurrently)."""
        from ray_tpu import api
        cw = api._worker
        wg = self.worker_group
        if cw is None or wg is None:
            return ""
        by_node: Dict[str, List[int]] = {}
        pid_rank: Dict[int, int] = {}
        for r in ranks:
            w = wg.workers[r]
            if w.node_id and w.pid:
                by_node.setdefault(w.node_id, []).append(w.pid)
                pid_rank[w.pid] = r
        lines: List[str] = []
        try:
            table = cw.io.run(cw._node_table(), timeout=10)
        except Exception:
            return ""
        for nid, pids in by_node.items():
            addr = table.get(nid)
            if not addr:
                continue
            try:
                reply = cw.io.run(cw.pool.get(addr).call(
                    "NodeManager", "CollectStacks", {"pids": pids},
                    timeout=10), timeout=15)
            except Exception as e:
                lines.append(f"[node {nid[:8]}] stack collection failed: "
                             f"{e!r}")
                continue
            for proc in reply.get("processes", []):
                rank = pid_rank.get(proc.get("pid"), "?")
                lines.append(f"[rank {rank} pid {proc.get('pid')} "
                             f"node {nid[:8]}]")
                if proc.get("error"):
                    lines.append(f"  probe error: {proc['error']}")
                for t in proc.get("threads", []):
                    lines.append(f"  thread {t.get('name')}:")
                    for sl in str(t.get("stack", "")).splitlines():
                        lines.append(f"    {sl}")
        return "\n".join(lines)

    # ---------------- lifecycle ----------------

    def finish_training(self):
        wg = self.worker_group
        ray_tpu.get([w.actor.finish_session.remote() for w in wg.workers],
                    timeout=120)

    def can_restart(self) -> bool:
        return (self._max_failures == -1
                or self._num_failures < self._max_failures)

    def latest_committed_checkpoint(self) -> Optional[Checkpoint]:
        """The newest COMMITTED step under the checkpoint manager, as a
        Checkpoint — what an elastic restart resumes from.  An async
        save the dead gang never committed is invisible here by
        construction (no COMMIT marker), so a restart can never resume
        from a torn checkpoint."""
        mgr = self.checkpoint_manager
        if mgr is None:
            return None
        try:
            mgr.wait_until_finished()   # drain any driver-side writer
        except Exception as e:
            logger.warning("async checkpoint write failed: %s", e)
        step = mgr.latest_step()
        if step is None:
            return None
        return Checkpoint.from_sharded_dir(mgr.step_dir(step))

    def restart(self, reason: str = "failure"):
        """Elastic restart: tear the gang down, rebuild, re-rendezvous
        (reference: backend_executor.py:583).  On TPU a lost host means
        the slice re-forms as a whole — per-worker restart is not a
        thing.  With min_workers set the rebuild may come back SMALLER
        (resize-down onto survivors) instead of waiting for the lost
        host's replacement."""
        t0 = time.monotonic()
        self._num_failures += 1
        self._generation += 1
        logger.warning("restarting worker group (failure %d/%s, "
                       "reason=%s)", self._num_failures,
                       self._max_failures, reason)
        self.shutdown()
        self.start()
        dt = time.monotonic() - t0
        _metrics()["train_recoveries"].inc(tags={"reason": reason})
        _metrics()["train_recovery_seconds"].observe(
            dt, tags={"reason": reason})
        from ray_tpu.util import events
        events.record("train", "recovery", reason=reason,
                      workers=len(self.worker_group),
                      seconds=round(dt, 3))
        logger.warning("gang re-formed with %d worker(s) in %.2fs",
                       len(self.worker_group), dt)

    def should_resize_up(self) -> bool:
        """True when a resized-down gang can grow back: capacity for the
        missing workers is available again (a preempted host returned).
        Rate-limited by train_resize_check_interval_s so the probe never
        taxes the step loop."""
        s = self._scaling
        if getattr(s, "min_workers", None) is None \
                or self.worker_group is None:
            return False
        cur = len(self.worker_group)
        if cur >= s.num_workers:
            return False
        now = time.monotonic()
        if now - self._last_resize_check \
                < _cfg().train_resize_check_interval_s:
            return False
        self._last_resize_check = now
        need = s.num_workers - cur
        demand = s.worker_resources()
        try:
            avail = ray_tpu.available_resources()
        except Exception:
            return False
        if not all(avail.get(k, 0.0) + 1e-9 >= v * need
                   for k, v in demand.items() if v > 0):
            self._resize_ready_since = None
            return False
        # Debounce: trust the surplus only once it has outlived the GCS
        # heartbeat lag (two ticks), so our own just-placed bundles —
        # still reading as free in a stale view — never trigger growth.
        if self._resize_ready_since is None:
            self._resize_ready_since = now
            return False
        settle = max(1.0, 2 * _cfg().heartbeat_interval_s)
        return now - self._resize_ready_since >= settle

    def resize_up(self, reason: str = "resize_up"):
        """Re-admit returned capacity at a step boundary: cooperatively
        stop the running sessions, tear down, and re-form at (up to)
        full size.  The caller resumes from the latest COMMITTED
        checkpoint, exactly like a failure restart — but this path is
        voluntary, so nothing counts against the failure budget."""
        t0 = time.monotonic()
        self._generation += 1
        wg = self.worker_group
        if wg is not None:
            try:
                ray_tpu.get([w.actor.stop_session.remote()
                             for w in wg.workers], timeout=10)
            except Exception:
                pass  # dead/stuck workers die with the gang teardown
        logger.warning("resize-up: re-forming gang at full size (%d)",
                       self._scaling.num_workers)
        self.shutdown()
        self.start()
        dt = time.monotonic() - t0
        _metrics()["train_recoveries"].inc(tags={"reason": reason})
        _metrics()["train_recovery_seconds"].observe(
            dt, tags={"reason": reason})
        from ray_tpu.util import events
        events.record("train", "recovery", reason=reason,
                      workers=len(self.worker_group),
                      seconds=round(dt, 3))
        logger.warning("gang re-formed with %d worker(s) in %.2fs",
                       len(self.worker_group), dt)

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
