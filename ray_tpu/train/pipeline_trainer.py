"""MPMD pipeline-parallel trainer: the driver-side schedule pump.

`PipelineTrainer` maps each pipeline stage to its own `StageGroup` (an
actor gang under its own placement group — see
`train/pipeline_stage.py`), then runs 1F1B or GPipe microbatch schedules
by pumping at most one compute op per gang member and letting activation
and gradient ObjectRefs flow stage-to-stage over the native object
plane.  The driver only ever fetches the small `meta` half of each
`num_returns=2` stage call; the payload ref is handed to the next stage
wrapped in a tuple so the bytes move shm-to-shm.

Backpressure: a stage may run at most `queue_depth` microbatches ahead
of its downstream consumer, and 1F1B additionally caps stage *i* at
``n_stages - i`` forwards not yet backward-ed (the classic warmup
depth), so queue growth is bounded and a stalled stage stalls its
upstream instead of ballooning the store.

Failure semantics (the headline):

- a dead gang member marks its whole stage dead (params are replicated
  but grad contributions are member-local); the stage re-forms in place
  via `StageGroup.reform()` — fresh PG, fresh actors through the zygote
  spawn path, params from the stage's latest COMMITTED checkpoint;
- if the restored version equals the in-flight step, recovery is
  *surgical*: only the dead stage's microbatches replay, re-fed from the
  upstream stage's sealed activations and the downstream stage's sealed
  grads (the node store outlives workers, so those refs stay readable);
  surviving stages never restart and never recompute;
- if the re-formed stage restored a *newer* version (it died after
  applying + committing the step), it is marked applied and skips the
  boundary;
- anything else — or a recovery that finds no dead stage (e.g. objects
  lost with a hostd) — falls back to a global rollback: every stage
  loads the newest checkpoint step committed by *all* stages (survivors
  load in place, without restarting), and `fit` resumes from there.

All recoveries count against `max_failures`.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.train.pipeline_stage import StageGroup

_M = None


def _metrics():
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "bubble": mt.Histogram(
                "pp_bubble_fraction",
                "per-step pipeline bubble fraction: 1 - busy/(members*wall)",
                buckets=(0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7,
                         0.8, 0.9, 1.0)),
            "recoveries": mt.Counter(
                "pp_recoveries",
                "per-stage pipeline recoveries by kind",
                tag_keys=("kind",)),
            "step": mt.Histogram(
                "pp_step_seconds", "pipeline train-step wall clock"),
        }
    return _M


def jax_stage_fns(stage_fn: Callable, loss_fn: Callable):
    """Build the (stage_fwd, stage_bwd, loss_fwd, loss_bwd) quartet from
    a jax ``stage_fn(params, x) -> y`` / ``loss_fn(y, target) -> scalar``
    pair via ``jax.vjp``.  The vjp closures live only inside the stage
    worker (caches are never shipped), and outputs cross stages as numpy.
    jax is imported lazily so numpy-only pipelines never pay for it."""

    def stage_fwd(params, x):
        import jax
        y, vjp = jax.vjp(stage_fn, params, x)
        return np.asarray(y), vjp

    def stage_bwd(params, vjp, gy):
        import jax.numpy as jnp
        gparams, gx = vjp(jnp.asarray(gy))
        import jax
        return np.asarray(gx), jax.tree.map(np.asarray, gparams)

    def loss_fwd(y, target):
        import jax
        loss, vjp = jax.vjp(loss_fn, y, target)
        return float(loss), vjp

    def loss_bwd(vjp):
        gy, _gt = vjp(1.0)
        return np.asarray(gy)

    return stage_fwd, stage_bwd, loss_fwd, loss_bwd


class _StageFailure(Exception):
    """Internal: a stage op failed; recovery should run."""

    def __init__(self, stage: int, reason: str):
        super().__init__(f"stage {stage}: {reason}")
        self.stage = stage
        self.reason = reason


class _Rollback(Exception):
    """Internal: global rollback to `step` (all stages reloaded)."""

    def __init__(self, step: int):
        super().__init__(f"rollback to step {step}")
        self.step = step


class _Op:
    __slots__ = ("stage", "member", "kind", "mb", "t")

    def __init__(self, stage, member, kind, mb):
        self.stage = stage
        self.member = member
        self.kind = kind
        self.mb = mb
        self.t = time.monotonic()


class _StepState:
    """Driver-side bookkeeping for one train step's schedule pump."""

    def __init__(self, n_stages: int, n_micro: int):
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.fwd_disp = [set() for _ in range(n_stages)]
        self.fwd_done = [set() for _ in range(n_stages)]
        self.bwd_disp = [set() for _ in range(n_stages)]
        self.bwd_done = [set() for _ in range(n_stages)]
        self.busy: List[Dict[int, Any]] = [dict() for _ in range(n_stages)]
        self.act: List[Dict[int, Any]] = [dict() for _ in range(n_stages)]
        self.gout: List[Dict[int, Any]] = [dict() for _ in range(n_stages)]
        self.losses: Dict[int, float] = {}
        self.pending: Dict[Any, _Op] = {}
        self.applied = [False] * n_stages

    def reset_stage(self, i: int):
        """Forget stage i's schedule progress (its gang re-formed with
        empty caches): every microbatch replays through stage i, nothing
        else changes.  Refs the stage produced earlier stay in act/gout
        maps until the replay overwrites them — consumers that already
        fetched them are unaffected (sealed objects are immutable)."""
        self.fwd_disp[i] = set()
        self.fwd_done[i] = set()
        self.bwd_disp[i] = set()
        self.bwd_done[i] = set()
        self.busy[i] = {}
        self.applied[i] = False
        self.pending = {r: op for r, op in self.pending.items()
                        if op.stage != i}

    def compute_done(self) -> bool:
        return all(self.applied[i]
                   or len(self.bwd_done[i]) == self.n_micro
                   for i in range(self.n_stages))


class PipelineTrainer:
    """Fault-tolerant MPMD pipeline-parallel SGD trainer.

    Args:
      stage_fns: (stage_fwd, stage_bwd, loss_fwd, loss_bwd) — see
        `pipeline_stage` module docs, or build from jax via
        `jax_stage_fns`.
      stage_params: list of per-stage param pytrees (numpy leaves);
        one entry per pipeline stage.
      n_microbatches: microbatches per global step.
      schedule: "1f1b" (bwd-first, bounded warmup) or "gpipe"
        (all-fwd-then-bwd).
      queue_depth: max microbatches a stage may run ahead of its
        downstream consumer (the inter-stage queue bound).
      workers_per_stage: gang size per stage (data parallel within a
        stage; microbatch j lands on member j % gang at every stage).
      storage_path: checkpoint root; per-stage trees commit under
        `<root>/stage_XX`.  None disables checkpointing (and therefore
        restart recovery — only surgical replay works).
      ckpt_every: commit per-stage checkpoints every k steps.
      max_failures: recoveries allowed across the fit before giving up.
      stage_timeout_s: op-completion watchdog; an op outstanding this
        long triggers a gang beacon probe.
    """

    def __init__(self, stage_fns: Tuple[Callable, Callable, Callable,
                                        Callable],
                 stage_params: List[Any], *, lr: float = 0.05,
                 n_microbatches: int = 8, schedule: str = "1f1b",
                 queue_depth: int = 2, workers_per_stage: int = 1,
                 resources_per_worker: Optional[dict] = None,
                 storage_path: Optional[str] = None, ckpt_every: int = 1,
                 max_failures: int = 2, stage_timeout_s: float = 30.0,
                 placement_strategy: str = "PACK",
                 pg_timeout_s: float = 60.0):
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.n_stages = len(stage_params)
        self.n_micro = int(n_microbatches)
        self.schedule = schedule
        self.queue_depth = max(1, int(queue_depth))
        self.gang = max(1, int(workers_per_stage))
        self.max_failures = int(max_failures)
        self.stage_timeout_s = float(stage_timeout_s)
        self.ckpt_every = max(1, int(ckpt_every))
        self.storage_path = storage_path
        self._recoveries = 0
        self.history: List[dict] = []
        fwd, bwd, loss_fwd, loss_bwd = stage_fns
        self.groups: List[StageGroup] = []
        try:
            for i, params in enumerate(stage_params):
                root = ""
                if storage_path:
                    import os
                    root = os.path.join(storage_path, f"stage_{i:02d}")
                spec = {"stage": i, "n_stages": self.n_stages,
                        "stage_fwd": fwd, "stage_bwd": bwd,
                        "loss_fwd": loss_fwd, "loss_bwd": loss_bwd,
                        "params": params, "lr": lr, "ckpt_root": root}
                self.groups.append(StageGroup(
                    i, spec, self.gang,
                    resources_per_worker or {"CPU": 1},
                    placement_strategy=placement_strategy,
                    pg_timeout_s=pg_timeout_s))
        except BaseException:
            self.shutdown()
            raise

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _member(self, mb: int) -> int:
        return mb % self.gang

    def _fwd_ready(self, st: _StepState, i: int, mb: int) -> bool:
        # Gate on the producer op having COMPLETED (activation sealed in
        # the node store), not on the ref existing: a dispatch-time ref
        # whose producer died unexecuted would feed the consumer a
        # poisoned object.
        if i == 0:
            return True
        return mb in st.fwd_done[i - 1]

    def _bwd_ready(self, st: _StepState, i: int, mb: int) -> bool:
        if mb not in st.fwd_done[i]:
            return False
        if i == self.n_stages - 1:
            return True
        return mb in st.bwd_done[i + 1]

    def _next_mb(self, disp: set, member: int) -> Optional[int]:
        for j in range(self.n_micro):
            if j not in disp and self._member(j) == member:
                return j
        return None

    def _fwd_window_ok(self, st: _StepState, i: int) -> bool:
        if self.schedule == "1f1b":
            warmup = max(1, self.n_stages - i)
            if len(st.fwd_disp[i]) - len(st.bwd_done[i]) >= warmup:
                return False
        if i + 1 < self.n_stages:
            # Bounded inter-stage queue: don't outrun the consumer.
            ahead = len(st.fwd_done[i]) - len(st.fwd_done[i + 1])
            if ahead >= self.queue_depth:
                return False
        return True

    def _dispatch(self, step: int, st: _StepState, mbs, tgts):
        last = self.n_stages - 1
        for i, grp in enumerate(self.groups):
            if st.applied[i]:
                continue
            for m, actor in enumerate(grp.members):
                if m in st.busy[i]:
                    continue
                jb = self._next_mb(st.bwd_disp[i], m)
                jf = self._next_mb(st.fwd_disp[i], m)
                do_bwd = (jb is not None and self._bwd_ready(st, i, jb))
                do_fwd = (jf is not None and self._fwd_ready(st, i, jf)
                          and self._fwd_window_ok(st, i))
                if self.schedule == "gpipe" and do_fwd:
                    do_bwd = False      # all forwards drain first
                if do_bwd:
                    gyw = None if i == last else ((st.gout[i + 1][jb],))
                    meta, gx = actor.backward.options(
                        num_returns=2).remote(step, jb, gyw)
                    st.gout[i][jb] = gx
                    st.bwd_disp[i].add(jb)
                    st.busy[i][m] = meta
                    st.pending[meta] = _Op(i, m, "bwd", jb)
                elif do_fwd:
                    xw = (mbs[jf],) if i == 0 else ((st.act[i - 1][jf],))
                    tw = (tgts[jf],) if i == last else None
                    meta, y = actor.forward.options(
                        num_returns=2).remote(step, jf, xw, tw)
                    if i != last:
                        st.act[i][jf] = y
                    st.fwd_disp[i].add(jf)
                    st.busy[i][m] = meta
                    st.pending[meta] = _Op(i, m, "fwd", jf)

    def _poll(self, st: _StepState):
        """Consume completed op metas; raise _StageFailure on death or
        on a silent stall past the op watchdog."""
        if not st.pending:
            time.sleep(0.005)
            return
        ready, _ = ray_tpu.wait(list(st.pending), num_returns=1,
                                timeout=0.2)
        for r in ready:
            op = st.pending.pop(r)
            st.busy[op.stage].pop(op.member, None)
            try:
                meta = ray_tpu.get(r)
            except (exceptions.ActorError, exceptions.WorkerCrashedError,
                    exceptions.ObjectLostError,
                    exceptions.TaskError) as e:
                # TaskError rides along: under node loss a replayed op
                # can fetch a ref whose bytes died with the store — the
                # rollback path, not a user bug (a genuine user error
                # re-raises once recoveries exhaust max_failures, with
                # this exception chained as the cause).
                raise _StageFailure(op.stage, type(e).__name__) from e
            if op.kind == "fwd":
                st.fwd_done[op.stage].add(op.mb)
                if op.stage == self.n_stages - 1:
                    st.losses[op.mb] = meta["loss"]
            else:
                st.bwd_done[op.stage].add(op.mb)
        if not ready and st.pending:
            now = time.monotonic()
            stale = [op for op in st.pending.values()
                     if now - op.t > self.stage_timeout_s]
            for op in stale:
                beacons = self.groups[op.stage].beacons(timeout=5.0)
                if any(b is None for b in beacons):
                    raise _StageFailure(op.stage, "beacon_lost")
                op.t = now      # alive but slow: re-arm the watchdog

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------

    def _probe_dead_stages(self) -> List[int]:
        dead = []
        for i, grp in enumerate(self.groups):
            if any(b is None for b in grp.beacons(timeout=5.0)):
                dead.append(i)
        return dead

    def _recover(self, step: int, st: _StepState, failure: _StageFailure):
        """Re-form dead gangs and pick the cheapest sound recovery.

        Raises _Rollback when per-stage surgical replay is not provably
        sufficient."""
        from ray_tpu.util import events, spans
        self._recoveries += 1
        if self._recoveries > self.max_failures:
            raise RuntimeError(
                f"pipeline exceeded max_failures={self.max_failures}"
            ) from failure
        with spans.span("pp", "recover", step=step,
                        reason=failure.reason):
            dead = self._probe_dead_stages()
            if failure.stage not in dead:
                beacons = self.groups[failure.stage].beacons(timeout=5.0)
                if any(b is None for b in beacons):
                    dead.append(failure.stage)
            events.record("pp", "stage_dead", step=step, stages=dead,
                          reason=failure.reason)
            if not dead:
                # The op failed but every gang answers (e.g. an object
                # was lost with its node): replay lineage is broken, so
                # fall back to the checkpoint intersection.
                _metrics()["recoveries"].inc(tags={"kind": "rollback"})
                self._rollback(step)
            for i in dead:
                version = self.groups[i].reform()
                restored = version if version is not None else 0
                if restored == step:
                    # Pre-apply params for the in-flight step: replay
                    # only this stage's microbatches (surgical).
                    events.record("pp", "replay", step=step, stage=i,
                                  n_micro=self.n_micro)
                    _metrics()["recoveries"].inc(tags={"kind": "replay"})
                    st.reset_stage(i)
                elif restored == step + 1:
                    # Died after apply+commit: nothing to replay and the
                    # boundary must not re-apply.  Done-sets read full so
                    # neighbours (which, having reached the boundary,
                    # already consumed this stage's sealed outputs) never
                    # wait on it.
                    _metrics()["recoveries"].inc(
                        tags={"kind": "already_applied"})
                    st.reset_stage(i)
                    full = set(range(self.n_micro))
                    st.fwd_disp[i] = set(full)
                    st.fwd_done[i] = set(full)
                    st.bwd_disp[i] = set(full)
                    st.bwd_done[i] = set(full)
                    st.applied[i] = True
                else:
                    _metrics()["recoveries"].inc(tags={"kind": "rollback"})
                    self._rollback(step)

    def _rollback(self, step: int):
        """Load the newest step committed by ALL stages everywhere (no
        gang restarts — survivors load in place), then unwind to `fit`."""
        from ray_tpu.util import events
        per_stage = []
        for grp in self.groups:
            try:
                steps = ray_tpu.get(
                    grp.members[0].committed_steps.remote(), timeout=30)
            except Exception:
                grp.reform()
                steps = ray_tpu.get(
                    grp.members[0].committed_steps.remote(), timeout=30)
            per_stage.append(set(steps))
        common = set.intersection(*per_stage) if per_stage else set()
        target = max(common) if common else None
        if target is None:
            # Nothing commonly committed: restart from initial params.
            for grp in self.groups:
                grp.shutdown()
                grp.incarnation += 1
                grp._form()
            events.record("pp", "rollback", step=step, to=0)
            raise _Rollback(0)
        refs = [a.load_ckpt.remote(target)
                for grp in self.groups for a in grp.members]
        ray_tpu.get(refs, timeout=120)
        events.record("pp", "rollback", step=step, to=target)
        raise _Rollback(target)

    # ------------------------------------------------------------------
    # step
    # ------------------------------------------------------------------

    def _boundary(self, step: int, st: _StepState):
        """Grad fold + SGD apply + per-stage checkpoint commit, all
        version-guarded so a mid-boundary death retries cleanly."""
        partials: Dict[int, list] = {}
        metas = {}
        for i, grp in enumerate(self.groups):
            if st.applied[i]:
                continue
            partials[i] = []
            for a in grp.members:
                meta, grads = a.partial_grads.options(
                    num_returns=2).remote(step)
                partials[i].append(grads)
                metas[meta] = i
        for meta, i in metas.items():
            try:
                ray_tpu.get(meta, timeout=self.stage_timeout_s)
            except (exceptions.ActorError, exceptions.WorkerCrashedError,
                    exceptions.ObjectLostError, exceptions.TaskError,
                    exceptions.RayTpuTimeoutError) as e:
                raise _StageFailure(
                    i, f"partial_grads:{type(e).__name__}") from e
        apply_refs: Dict[int, list] = {}
        for i, grp in enumerate(self.groups):
            if st.applied[i]:
                continue
            apply_refs[i] = [a.apply_update.remote(
                step, partials[i], self.n_micro) for a in grp.members]
        busy = idle = 0.0
        for i, refs in apply_refs.items():
            try:
                for out in ray_tpu.get(refs, timeout=self.stage_timeout_s):
                    busy += out.get("busy_s", 0.0)
                    idle += out.get("idle_s", 0.0)
            except (exceptions.ActorError, exceptions.WorkerCrashedError,
                    exceptions.ObjectLostError, exceptions.TaskError,
                    exceptions.RayTpuTimeoutError) as e:
                raise _StageFailure(
                    i, f"apply_update:{type(e).__name__}") from e
            # This stage's gang fully applied: a boundary retry after a
            # later stage's death must not re-enter it.
            st.applied[i] = True
        if self.storage_path and (step + 1) % self.ckpt_every == 0:
            saves = {grp.members[0].save_ckpt.remote(step + 1): i
                     for i, grp in enumerate(self.groups)}
            for ref, i in saves.items():
                try:
                    ray_tpu.get(ref, timeout=90)
                except (exceptions.ActorError,
                        exceptions.WorkerCrashedError,
                        exceptions.TaskError,
                        exceptions.RayTpuTimeoutError) as e:
                    raise _StageFailure(
                        i, f"save_ckpt:{type(e).__name__}") from e
        return busy, idle

    def _train_step(self, step: int, mbs, tgts) -> dict:
        from ray_tpu.util import spans
        st = _StepState(self.n_stages, self.n_micro)
        t0 = time.monotonic()
        with spans.span("pp", "step", step=step,
                        n_micro=self.n_micro):
            while True:
                try:
                    while not st.compute_done():
                        self._dispatch(step, st, mbs, tgts)
                        self._poll(st)
                    busy, idle = self._boundary(step, st)
                    break
                except _StageFailure as f:
                    self._recover(step, st, f)
        wall = time.monotonic() - t0
        members = self.n_stages * self.gang
        bubble = max(0.0, 1.0 - busy / (members * wall)) if wall > 0 \
            else 0.0
        _metrics()["bubble"].observe(bubble)
        _metrics()["step"].observe(wall)
        loss = (sum(st.losses.values()) / len(st.losses)
                if st.losses else float("nan"))
        return {"step": step, "loss": loss, "wall_s": wall,
                "bubble_fraction": bubble, "busy_s": busy, "idle_s": idle,
                "recoveries": self._recoveries}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def fit(self, data_fn: Callable[[int], Tuple[list, list]],
            num_steps: int) -> List[dict]:
        """Run `num_steps` pipeline steps.  ``data_fn(step)`` returns
        (microbatches, targets) — it must be deterministic per step,
        because a rollback re-requests earlier steps' data."""
        s = 0
        while s < num_steps:
            xs, ts = data_fn(s)
            if len(xs) != self.n_micro or len(ts) != self.n_micro:
                raise ValueError(
                    f"data_fn(step) must return {self.n_micro} "
                    f"microbatches, got {len(xs)}/{len(ts)}")
            mbs = [ray_tpu.put(np.asarray(x)) for x in xs]
            tgts = [ray_tpu.put(np.asarray(t)) for t in ts]
            try:
                rec = self._train_step(s, mbs, tgts)
            except _Rollback as rb:
                s = rb.step
                continue
            self.history.append(rec)
            s += 1
        return self.history

    def forward_only(self, xs: list, ts: list) -> float:
        """One fwd-only pass over the schedule; returns the mean loss.
        No recovery (parity/bench probe).  Leaves no per-step state."""
        st = _StepState(self.n_stages, self.n_micro)
        mbs = [ray_tpu.put(np.asarray(x)) for x in xs]
        tgts = [ray_tpu.put(np.asarray(t)) for t in ts]
        # Forward-only wants no bwd dispatch: mark bwd complete up front.
        for i in range(self.n_stages):
            st.bwd_disp[i] = set(range(self.n_micro))
            st.bwd_done[i] = set(range(self.n_micro))
        while not all(len(st.fwd_done[i]) == self.n_micro
                      for i in range(self.n_stages)):
            self._dispatch(0, st, mbs, tgts)
            self._poll(st)
        ray_tpu.get([a.reset_step.remote(0)
                     for g in self.groups for a in g.members], timeout=60)
        return sum(st.losses.values()) / len(st.losses)

    def stage_idents(self) -> List[List[dict]]:
        return [list(grp.idents) for grp in self.groups]

    def shutdown(self):
        for grp in self.groups:
            try:
                grp.shutdown()
            except Exception:
                pass
        self.groups = []
