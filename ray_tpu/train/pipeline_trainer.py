"""MPMD pipeline-parallel trainer: the driver-side schedule pump.

`PipelineTrainer` splits the model into `n_chunks = len(stage_params)`
stage-chunks and maps them round-robin onto `n_chunks // interleave`
actor gangs (each a `StageGroup` under its own placement group — see
`train/pipeline_stage.py`), then runs 1F1B or GPipe microbatch
schedules by pumping at most one compute op per gang member and letting
activation and gradient ObjectRefs flow chunk-to-chunk over the native
object plane.  The driver only ever fetches the small `meta` half of
each `num_returns=2` stage call; the payload ref is handed to the next
chunk wrapped in a tuple so the bytes move shm-to-shm.

Three levers take transfer and bubble off the critical path:

- **Interleaved (looping) schedule** — with ``interleave=v > 1`` each
  gang owns v *non-adjacent* chunks (gang g owns ``g, g+n_gangs, ...``),
  so during warmup/drain every gang has some chunk with work and the
  classic bubble shrinks by ~1/v.  Per-(chunk, microbatch) grads fold
  in sorted order at the boundary, so the SGD trajectory is
  bit-identical to the v=1 1F1B/GPipe runs.
- **Pre-pushed activations** (``prefetch=True``) — the moment chunk c's
  forward for microbatch m completes (activation sealed in the node
  store), the driver ships the ref to chunk c+1's owner via
  ``prefetch``, which resolves it on a spare actor thread concurrently
  with that gang's compute (`pp/xfer_overlap`), parking the bytes in a
  double-buffered receive window (`recv_window`).  The consuming
  forward takes the resident copy for free instead of blocking inside
  `pp/xfer`.
- **Topology-aware placement** (``placement_plan``) — a per-gang extra
  resource dict (see `parallel.mesh.stage_slice_plan` /
  `pipeline_placement_resources`, built on the same slice discipline as
  `create_two_level_mesh`/`slice_index_of`) pins each gang inside one
  ICI slice so adjacent chunks transfer ICI-near and the pipeline is
  cut only at DCN boundaries; gang members themselves form the
  intra-stage DP mesh (microbatch j lands on member j % gang), giving
  DP x (per-worker TP) x PP.

Backpressure: chunk *c* may complete at most `queue_depth` forwards
ahead of chunk *c+1*, and in-flight pre-pushed activations count
against the consumer's memory on top of that — the dispatcher blocks a
forward when ``(sealed-unconsumed) + (resident prefetched) >=
queue_depth + recv_window``, so double-buffering can never grow a
stage's memory unbounded.  1F1B additionally caps chunk *c* at
``n_chunks - c`` forwards not yet backward-ed (the classic warmup
depth).

Failure semantics (the headline):

- a dead gang member marks its whole gang dead (params are replicated
  but grad contributions are member-local); the gang re-forms in place
  via `StageGroup.reform()` — fresh PG, fresh actors through the zygote
  spawn path, params (every owned chunk) from the gang's latest
  COMMITTED checkpoint;
- if the restored version equals the in-flight step, recovery is
  *surgical*: only the dead gang's chunks replay their microbatches,
  re-fed (and re-pushed) from upstream chunks' sealed activations and
  downstream chunks' sealed grads (the node store outlives workers, so
  those refs stay readable); surviving gangs never restart and never
  recompute.  Prefetched-but-unconsumed activations are replayable
  state: replayed producers reseal bit-identical bytes, so a consumer
  holding a pre-kill pushed copy cannot diverge;
- if the re-formed gang restored a *newer* version (it died after
  applying + committing the step), it is marked applied and skips the
  boundary;
- anything else — or a recovery that finds no dead gang (e.g. objects
  lost with a hostd) — falls back to a global rollback: every gang
  loads the newest checkpoint step committed by *all* gangs (survivors
  load in place, without restarting), and `fit` resumes from there.

All recoveries count against `max_failures`.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.train.pipeline_stage import StageGroup

_M = None


def _metrics():
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "bubble": mt.Histogram(
                "pp_bubble_fraction",
                "per-step pipeline bubble fraction: 1 - busy/(members*wall)",
                buckets=(0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7,
                         0.8, 0.9, 1.0)),
            "recoveries": mt.Counter(
                "pp_recoveries",
                "per-stage pipeline recoveries by kind",
                tag_keys=("kind",)),
            "step": mt.Histogram(
                "pp_step_seconds", "pipeline train-step wall clock"),
            "prepush": mt.Counter(
                "pp_prepush_total",
                "activations pre-pushed into downstream receive windows"),
        }
    return _M


def jax_stage_fns(stage_fn: Callable, loss_fn: Callable):
    """Build the (stage_fwd, stage_bwd, loss_fwd, loss_bwd) quartet from
    a jax ``stage_fn(params, x) -> y`` / ``loss_fn(y, target) -> scalar``
    pair via ``jax.vjp``.  The vjp closures live only inside the stage
    worker (caches are never shipped), and outputs cross stages as numpy.
    jax is imported lazily so numpy-only pipelines never pay for it."""

    def stage_fwd(params, x):
        import jax
        y, vjp = jax.vjp(stage_fn, params, x)
        return np.asarray(y), vjp

    def stage_bwd(params, vjp, gy):
        import jax.numpy as jnp
        gparams, gx = vjp(jnp.asarray(gy))
        import jax
        return np.asarray(gx), jax.tree.map(np.asarray, gparams)

    def loss_fwd(y, target):
        import jax
        loss, vjp = jax.vjp(loss_fn, y, target)
        return float(loss), vjp

    def loss_bwd(vjp):
        gy, _gt = vjp(1.0)
        return np.asarray(gy)

    return stage_fwd, stage_bwd, loss_fwd, loss_bwd


class _StageFailure(Exception):
    """Internal: a gang op failed; recovery should run."""

    def __init__(self, gang: int, reason: str):
        super().__init__(f"gang {gang}: {reason}")
        self.stage = gang
        self.reason = reason


class _Rollback(Exception):
    """Internal: global rollback to `step` (all gangs reloaded)."""

    def __init__(self, step: int):
        super().__init__(f"rollback to step {step}")
        self.step = step


class _Op:
    __slots__ = ("gang", "chunk", "member", "kind", "mb", "t")

    def __init__(self, gang, chunk, member, kind, mb):
        self.gang = gang
        self.chunk = chunk
        self.member = member
        self.kind = kind
        self.mb = mb
        self.t = time.monotonic()


class _StepState:
    """Driver-side bookkeeping for one train step's schedule pump.
    Schedule progress is per CHUNK; busy/applied are per GANG (a member
    runs one op at a time across all its owned chunks)."""

    def __init__(self, n_chunks: int, n_gangs: int, n_micro: int):
        self.n_chunks = n_chunks
        self.n_gangs = n_gangs
        self.n_micro = n_micro
        self.owner = [c % n_gangs for c in range(n_chunks)]
        self.fwd_disp = [set() for _ in range(n_chunks)]
        self.fwd_done = [set() for _ in range(n_chunks)]
        self.bwd_disp = [set() for _ in range(n_chunks)]
        self.bwd_done = [set() for _ in range(n_chunks)]
        # Microbatches whose activation ref was pre-pushed into chunk
        # c's receive window this step (the send queue's memory bound).
        self.prepushed = [set() for _ in range(n_chunks)]
        self.busy: List[Dict[int, Any]] = [dict() for _ in range(n_gangs)]
        self.act: List[Dict[int, Any]] = [dict() for _ in range(n_chunks)]
        self.gout: List[Dict[int, Any]] = [dict() for _ in range(n_chunks)]
        self.losses: Dict[int, float] = {}
        self.pending: Dict[Any, _Op] = {}
        self.applied = [False] * n_gangs

    def reset_gang(self, g: int):
        """Forget gang g's schedule progress (it re-formed with empty
        caches and an empty receive window): every microbatch replays
        through every chunk g owns, nothing else changes.  Refs its
        chunks produced earlier stay in act/gout maps until the replay
        overwrites them — consumers that already fetched them are
        unaffected (sealed objects are immutable, and the stage fns are
        deterministic so replayed bytes are identical)."""
        for c in range(self.n_chunks):
            if self.owner[c] != g:
                continue
            self.fwd_disp[c] = set()
            self.fwd_done[c] = set()
            self.bwd_disp[c] = set()
            self.bwd_done[c] = set()
            self.prepushed[c] = set()    # fresh actors, empty windows
        self.busy[g] = {}
        self.applied[g] = False
        self.pending = {r: op for r, op in self.pending.items()
                        if op.gang != g}

    def mark_gang_applied(self, g: int):
        full = set(range(self.n_micro))
        for c in range(self.n_chunks):
            if self.owner[c] != g:
                continue
            self.fwd_disp[c] = set(full)
            self.fwd_done[c] = set(full)
            self.bwd_disp[c] = set(full)
            self.bwd_done[c] = set(full)
        self.applied[g] = True

    def compute_done(self) -> bool:
        return all(self.applied[self.owner[c]]
                   or len(self.bwd_done[c]) == self.n_micro
                   for c in range(self.n_chunks))


class PipelineTrainer:
    """Fault-tolerant MPMD pipeline-parallel SGD trainer.

    Args:
      stage_fns: (stage_fwd, stage_bwd, loss_fwd, loss_bwd) — see
        `pipeline_stage` module docs, or build from jax via
        `jax_stage_fns`.
      stage_params: list of per-chunk param pytrees (numpy leaves); one
        entry per pipeline stage-chunk.
      n_microbatches: microbatches per global step.
      schedule: "1f1b" (bwd-first, bounded warmup) or "gpipe"
        (all-fwd-then-bwd).
      queue_depth: max microbatches a chunk may run ahead of its
        downstream consumer (the inter-stage queue bound).
      workers_per_stage: gang size (data parallel within a gang;
        microbatch j lands on member j % gang at every chunk).
      interleave: chunks per gang (v).  `len(stage_params)` must divide
        evenly; gang g owns chunks ``g, g+n_gangs, ...`` (non-adjacent).
      prefetch: pre-push sealed activations into downstream receive
        windows so `pp/xfer` resolves concurrently with compute.
      recv_window: max pre-pushed activations resident per chunk in a
        consumer's receive window (2 = double-buffered).
      placement_plan: optional per-gang extra resource dicts (length
        n_gangs) merged into each gang's bundle specs — the
        topology-aware placement hook (see
        `parallel.mesh.pipeline_placement_resources`).
      storage_path: checkpoint root; per-gang trees commit under
        `<root>/stage_XX`.  None disables checkpointing (and therefore
        restart recovery — only surgical replay works).
      ckpt_every: commit per-gang checkpoints every k steps.
      max_failures: recoveries allowed across the fit before giving up.
      stage_timeout_s: op-completion watchdog; an op outstanding this
        long triggers a gang beacon probe.
    """

    def __init__(self, stage_fns: Tuple[Callable, Callable, Callable,
                                        Callable],
                 stage_params: List[Any], *, lr: float = 0.05,
                 n_microbatches: int = 8, schedule: str = "1f1b",
                 queue_depth: int = 2, workers_per_stage: int = 1,
                 interleave: int = 1, prefetch: bool = False,
                 recv_window: int = 2,
                 resources_per_worker: Optional[dict] = None,
                 placement_plan: Optional[List[dict]] = None,
                 storage_path: Optional[str] = None, ckpt_every: int = 1,
                 max_failures: int = 2, stage_timeout_s: float = 30.0,
                 placement_strategy: str = "PACK",
                 pg_timeout_s: float = 60.0):
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.n_chunks = len(stage_params)
        self.v = max(1, int(interleave))
        if self.n_chunks % self.v:
            raise ValueError(
                f"interleave={self.v} must divide the {self.n_chunks} "
                f"stage-chunks evenly")
        self.n_gangs = self.n_chunks // self.v
        self.n_stages = self.n_chunks           # end-to-end chunk count
        self.n_micro = int(n_microbatches)
        self.schedule = schedule
        self.queue_depth = max(1, int(queue_depth))
        self.prefetch = bool(prefetch)
        self.recv_window = max(1, int(recv_window))
        self.gang = max(1, int(workers_per_stage))
        self.max_failures = int(max_failures)
        self.stage_timeout_s = float(stage_timeout_s)
        self.ckpt_every = max(1, int(ckpt_every))
        self.storage_path = storage_path
        self._recoveries = 0
        self.history: List[dict] = []
        if placement_plan is not None and len(placement_plan) != \
                self.n_gangs:
            raise ValueError(
                f"placement_plan has {len(placement_plan)} entries for "
                f"{self.n_gangs} gangs")
        fwd, bwd, loss_fwd, loss_bwd = stage_fns
        # Round-robin ownership — must match parallel.pipeline.
        # chunk_assignment (tests pin the equivalence); not imported
        # here so numpy-only pipelines never pay the jax import.
        self._assignment = [list(range(g, self.n_chunks, self.n_gangs))
                            for g in range(self.n_gangs)]
        self.groups: List[StageGroup] = []
        try:
            for g in range(self.n_gangs):
                chunks = self._assignment[g]
                root = ""
                if storage_path:
                    import os
                    root = os.path.join(storage_path, f"stage_{g:02d}")
                spec = {"stage": g, "n_stages": self.n_chunks,
                        "chunks": chunks,
                        "stage_fwd": fwd, "stage_bwd": bwd,
                        "loss_fwd": loss_fwd, "loss_bwd": loss_bwd,
                        "params": {c: stage_params[c] for c in chunks},
                        "lr": lr, "ckpt_root": root}
                res = dict(resources_per_worker or {"CPU": 1})
                if placement_plan is not None:
                    res.update(placement_plan[g])
                self.groups.append(StageGroup(
                    g, spec, self.gang, res,
                    placement_strategy=placement_strategy,
                    pg_timeout_s=pg_timeout_s))
            if placement_plan is not None:
                from ray_tpu.util import events
                events.record(
                    "pp", "placement", gangs=self.n_gangs,
                    interleave=self.v,
                    plan=[sorted(p) for p in placement_plan])
        except BaseException:
            self.shutdown()
            raise

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _member(self, mb: int) -> int:
        return mb % self.gang

    def _owner(self, c: int) -> int:
        return c % self.n_gangs

    def _chunks_of(self, g: int) -> List[int]:
        return list(range(g, self.n_chunks, self.n_gangs))

    def _fwd_ready(self, st: _StepState, c: int, mb: int) -> bool:
        # Gate on the producer op having COMPLETED (activation sealed in
        # the node store), not on the ref existing: a dispatch-time ref
        # whose producer died unexecuted would feed the consumer a
        # poisoned object.
        if c == 0:
            return True
        return mb in st.fwd_done[c - 1]

    def _bwd_ready(self, st: _StepState, c: int, mb: int) -> bool:
        if mb not in st.fwd_done[c]:
            return False
        if c == self.n_chunks - 1:
            return True
        return mb in st.bwd_done[c + 1]

    def _next_mb(self, disp: set, member: int) -> Optional[int]:
        for j in range(self.n_micro):
            if j not in disp and self._member(j) == member:
                return j
        return None

    def _fwd_window_ok(self, st: _StepState, c: int) -> bool:
        if self.schedule == "1f1b":
            warmup = max(1, self.n_chunks - c)
            if len(st.fwd_disp[c]) - len(st.bwd_done[c]) >= warmup:
                return False
        if c + 1 < self.n_chunks:
            # Bounded inter-stage queue: don't outrun the consumer.
            # Sealed-but-unconsumed activations count against
            # queue_depth; activations pre-pushed into the consumer's
            # receive window but not yet consumed occupy a SECOND copy
            # of the bytes (store + window), so the combined bound is
            # queue_depth + recv_window — double-buffering can't grow
            # the consumer's memory without stalling the producer.
            ahead = len(st.fwd_done[c]) - len(st.fwd_done[c + 1])
            if ahead >= self.queue_depth:
                return False
            resident = len(st.prepushed[c + 1] - st.fwd_disp[c + 1])
            if ahead + resident >= self.queue_depth + self.recv_window:
                return False
        return True

    def _pump_prefetch(self, step: int, st: _StepState, mbs):
        """Ship sealed activation refs into downstream receive windows,
        bounded per chunk by recv_window (resident = pushed but not yet
        consumed by a dispatched forward)."""
        from ray_tpu.util import events
        # Chunk 0 is fed from driver-local puts — nothing to hide there,
        # so pre-push only real inter-stage activations (c >= 1).
        for c in range(1, self.n_chunks):
            g = self._owner(c)
            if st.applied[g]:
                continue
            resident = len(st.prepushed[c] - st.fwd_disp[c])
            if resident >= self.recv_window:
                continue
            ready = sorted(st.fwd_done[c - 1])
            for mb in ready:
                if mb in st.prepushed[c] or mb in st.fwd_disp[c]:
                    continue
                src = st.act[c - 1][mb]
                actor = self.groups[g].members[self._member(mb)]
                # Fire-and-forget: a failed prefetch surfaces through
                # the consuming forward (parked error) or the watchdog.
                actor.prefetch.remote(step, c, mb, (src,))
                events.record("pp", "prepush", step=step, chunk=c, mb=mb)
                _metrics()["prepush"].inc()
                st.prepushed[c].add(mb)
                resident += 1
                if resident >= self.recv_window:
                    break

    def _pick_bwd(self, st: _StepState, g: int, m: int):
        # Deepest owned chunk first: drains the pipeline and frees the
        # 1F1B warmup window of shallower chunks soonest.
        for c in reversed(self._chunks_of(g)):
            jb = self._next_mb(st.bwd_disp[c], m)
            if jb is not None and self._bwd_ready(st, c, jb):
                return c, jb
        return None

    def _pick_fwd(self, st: _StepState, g: int, m: int):
        # Shallowest owned chunk first: keeps feeding the pipeline so
        # downstream gangs exit warmup as early as possible.
        for c in self._chunks_of(g):
            jf = self._next_mb(st.fwd_disp[c], m)
            if jf is not None and self._fwd_ready(st, c, jf) \
                    and self._fwd_window_ok(st, c):
                return c, jf
        return None

    def _dispatch(self, step: int, st: _StepState, mbs, tgts):
        if self.prefetch:
            self._pump_prefetch(step, st, mbs)
        last = self.n_chunks - 1
        for g, grp in enumerate(self.groups):
            if st.applied[g]:
                continue
            for m, actor in enumerate(grp.members):
                if m in st.busy[g]:
                    continue
                pb = self._pick_bwd(st, g, m)
                pf = self._pick_fwd(st, g, m)
                if self.schedule == "gpipe" and pf is not None:
                    pb = None           # all forwards drain first
                if pb is not None:
                    c, jb = pb
                    gyw = None if c == last else ((st.gout[c + 1][jb],))
                    meta, gx = actor.backward.options(
                        num_returns=2).remote(step, c, jb, gyw)
                    st.gout[c][jb] = gx
                    st.bwd_disp[c].add(jb)
                    st.busy[g][m] = meta
                    st.pending[meta] = _Op(g, c, m, "bwd", jb)
                elif pf is not None:
                    c, jf = pf
                    xw = (mbs[jf],) if c == 0 else ((st.act[c - 1][jf],))
                    tw = (tgts[jf],) if c == last else None
                    meta, y = actor.forward.options(
                        num_returns=2).remote(step, c, jf, xw, tw)
                    if c != last:
                        st.act[c][jf] = y
                    st.fwd_disp[c].add(jf)
                    st.busy[g][m] = meta
                    st.pending[meta] = _Op(g, c, m, "fwd", jf)

    def _poll(self, st: _StepState):
        """Consume completed op metas; raise _StageFailure on death or
        on a silent stall past the op watchdog."""
        if not st.pending:
            time.sleep(0.005)
            return
        ready, _ = ray_tpu.wait(list(st.pending), num_returns=1,
                                timeout=0.2)
        for r in ready:
            op = st.pending.pop(r)
            st.busy[op.gang].pop(op.member, None)
            try:
                meta = ray_tpu.get(r)
            except (exceptions.ActorError, exceptions.WorkerCrashedError,
                    exceptions.ObjectLostError,
                    exceptions.TaskError) as e:
                # TaskError rides along: under node loss a replayed op
                # can fetch a ref whose bytes died with the store — the
                # rollback path, not a user bug (a genuine user error
                # re-raises once recoveries exhaust max_failures, with
                # this exception chained as the cause).
                raise _StageFailure(op.gang, type(e).__name__) from e
            if op.kind == "fwd":
                st.fwd_done[op.chunk].add(op.mb)
                if op.chunk == self.n_chunks - 1:
                    st.losses[op.mb] = meta["loss"]
            else:
                st.bwd_done[op.chunk].add(op.mb)
        if not ready and st.pending:
            now = time.monotonic()
            stale = [op for op in st.pending.values()
                     if now - op.t > self.stage_timeout_s]
            for op in stale:
                beacons = self.groups[op.gang].beacons(timeout=5.0)
                if any(b is None for b in beacons):
                    raise _StageFailure(op.gang, "beacon_lost")
                op.t = now      # alive but slow: re-arm the watchdog

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------

    def _probe_dead_stages(self) -> List[int]:
        dead = []
        for g, grp in enumerate(self.groups):
            if any(b is None for b in grp.beacons(timeout=5.0)):
                dead.append(g)
        return dead

    def _recover(self, step: int, st: _StepState, failure: _StageFailure):
        """Re-form dead gangs and pick the cheapest sound recovery.

        Raises _Rollback when per-gang surgical replay is not provably
        sufficient."""
        from ray_tpu.util import events, spans
        self._recoveries += 1
        if self._recoveries > self.max_failures:
            raise RuntimeError(
                f"pipeline exceeded max_failures={self.max_failures}"
            ) from failure
        with spans.span("pp", "recover", step=step,
                        reason=failure.reason):
            dead = self._probe_dead_stages()
            if failure.stage not in dead:
                beacons = self.groups[failure.stage].beacons(timeout=5.0)
                if any(b is None for b in beacons):
                    dead.append(failure.stage)
            events.record("pp", "stage_dead", step=step, stages=dead,
                          reason=failure.reason)
            if not dead:
                # The op failed but every gang answers (e.g. an object
                # was lost with its node): replay lineage is broken, so
                # fall back to the checkpoint intersection.
                _metrics()["recoveries"].inc(tags={"kind": "rollback"})
                self._rollback(step)
            for g in dead:
                version = self.groups[g].reform()
                restored = version if version is not None else 0
                if restored == step:
                    # Pre-apply params for the in-flight step: replay
                    # only this gang's chunks (surgical).
                    events.record("pp", "replay", step=step, stage=g,
                                  n_micro=self.n_micro)
                    _metrics()["recoveries"].inc(tags={"kind": "replay"})
                    st.reset_gang(g)
                elif restored == step + 1:
                    # Died after apply+commit: nothing to replay and the
                    # boundary must not re-apply.  Done-sets read full so
                    # neighbours (which, having reached the boundary,
                    # already consumed this gang's sealed outputs) never
                    # wait on it.
                    _metrics()["recoveries"].inc(
                        tags={"kind": "already_applied"})
                    st.reset_gang(g)
                    st.mark_gang_applied(g)
                else:
                    _metrics()["recoveries"].inc(tags={"kind": "rollback"})
                    self._rollback(step)

    def _rollback(self, step: int):
        """Load the newest step committed by ALL gangs everywhere (no
        gang restarts — survivors load in place), then unwind to `fit`."""
        from ray_tpu.util import events
        per_stage = []
        for grp in self.groups:
            try:
                steps = ray_tpu.get(
                    grp.members[0].committed_steps.remote(), timeout=30)
            except Exception:
                grp.reform()
                steps = ray_tpu.get(
                    grp.members[0].committed_steps.remote(), timeout=30)
            per_stage.append(set(steps))
        common = set.intersection(*per_stage) if per_stage else set()
        target = max(common) if common else None
        if target is None:
            # Nothing commonly committed: restart from initial params.
            for grp in self.groups:
                grp.shutdown()
                grp.incarnation += 1
                grp._form()
            events.record("pp", "rollback", step=step, to=0)
            raise _Rollback(0)
        refs = [a.load_ckpt.remote(target)
                for grp in self.groups for a in grp.members]
        ray_tpu.get(refs, timeout=120)
        events.record("pp", "rollback", step=step, to=target)
        raise _Rollback(target)

    # ------------------------------------------------------------------
    # step
    # ------------------------------------------------------------------

    def _boundary(self, step: int, st: _StepState):
        """Grad fold + SGD apply + per-gang checkpoint commit, all
        version-guarded so a mid-boundary death retries cleanly."""
        partials: Dict[int, list] = {}
        metas = {}
        for g, grp in enumerate(self.groups):
            if st.applied[g]:
                continue
            partials[g] = []
            for a in grp.members:
                meta, grads = a.partial_grads.options(
                    num_returns=2).remote(step)
                partials[g].append(grads)
                metas[meta] = g
        for meta, g in metas.items():
            try:
                ray_tpu.get(meta, timeout=self.stage_timeout_s)
            except (exceptions.ActorError, exceptions.WorkerCrashedError,
                    exceptions.ObjectLostError, exceptions.TaskError,
                    exceptions.RayTpuTimeoutError) as e:
                raise _StageFailure(
                    g, f"partial_grads:{type(e).__name__}") from e
        apply_refs: Dict[int, list] = {}
        for g, grp in enumerate(self.groups):
            if st.applied[g]:
                continue
            apply_refs[g] = [a.apply_update.remote(
                step, partials[g], self.n_micro) for a in grp.members]
        busy = idle = 0.0
        for g, refs in apply_refs.items():
            try:
                for out in ray_tpu.get(refs, timeout=self.stage_timeout_s):
                    busy += out.get("busy_s", 0.0)
                    idle += out.get("idle_s", 0.0)
            except (exceptions.ActorError, exceptions.WorkerCrashedError,
                    exceptions.ObjectLostError, exceptions.TaskError,
                    exceptions.RayTpuTimeoutError) as e:
                raise _StageFailure(
                    g, f"apply_update:{type(e).__name__}") from e
            # This gang fully applied: a boundary retry after a later
            # gang's death must not re-enter it.
            st.applied[g] = True
        if self.storage_path and (step + 1) % self.ckpt_every == 0:
            saves = {grp.members[0].save_ckpt.remote(step + 1): g
                     for g, grp in enumerate(self.groups)}
            for ref, g in saves.items():
                try:
                    ray_tpu.get(ref, timeout=90)
                except (exceptions.ActorError,
                        exceptions.WorkerCrashedError,
                        exceptions.TaskError,
                        exceptions.RayTpuTimeoutError) as e:
                    raise _StageFailure(
                        g, f"save_ckpt:{type(e).__name__}") from e
        return busy, idle

    def _train_step(self, step: int, mbs, tgts) -> dict:
        from ray_tpu.util import spans
        st = _StepState(self.n_chunks, self.n_gangs, self.n_micro)
        t0 = time.monotonic()
        with spans.span("pp", "step", step=step, n_micro=self.n_micro,
                        interleave=self.v):
            while True:
                try:
                    while not st.compute_done():
                        self._dispatch(step, st, mbs, tgts)
                        self._poll(st)
                    busy, idle = self._boundary(step, st)
                    break
                except _StageFailure as f:
                    self._recover(step, st, f)
        wall = time.monotonic() - t0
        members = self.n_gangs * self.gang
        bubble = max(0.0, 1.0 - busy / (members * wall)) if wall > 0 \
            else 0.0
        _metrics()["bubble"].observe(bubble)
        _metrics()["step"].observe(wall)
        loss = (sum(st.losses.values()) / len(st.losses)
                if st.losses else float("nan"))
        return {"step": step, "loss": loss, "wall_s": wall,
                "bubble_fraction": bubble, "busy_s": busy, "idle_s": idle,
                "recoveries": self._recoveries}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def fit(self, data_fn: Callable[[int], Tuple[list, list]],
            num_steps: int) -> List[dict]:
        """Run `num_steps` pipeline steps.  ``data_fn(step)`` returns
        (microbatches, targets) — it must be deterministic per step,
        because a rollback re-requests earlier steps' data."""
        s = 0
        while s < num_steps:
            xs, ts = data_fn(s)
            if len(xs) != self.n_micro or len(ts) != self.n_micro:
                raise ValueError(
                    f"data_fn(step) must return {self.n_micro} "
                    f"microbatches, got {len(xs)}/{len(ts)}")
            mbs = [ray_tpu.put(np.asarray(x)) for x in xs]
            tgts = [ray_tpu.put(np.asarray(t)) for t in ts]
            try:
                rec = self._train_step(s, mbs, tgts)
            except _Rollback as rb:
                s = rb.step
                continue
            self.history.append(rec)
            s += 1
        return self.history

    def forward_only(self, xs: list, ts: list) -> float:
        """One fwd-only pass over the schedule; returns the mean loss.
        No recovery (parity/bench probe).  Leaves no per-step state."""
        st = _StepState(self.n_chunks, self.n_gangs, self.n_micro)
        mbs = [ray_tpu.put(np.asarray(x)) for x in xs]
        tgts = [ray_tpu.put(np.asarray(t)) for t in ts]
        # Forward-only wants no bwd dispatch: mark bwd complete up front.
        for c in range(self.n_chunks):
            st.bwd_disp[c] = set(range(self.n_micro))
            st.bwd_done[c] = set(range(self.n_micro))
        while not all(len(st.fwd_done[c]) == self.n_micro
                      for c in range(self.n_chunks)):
            self._dispatch(0, st, mbs, tgts)
            self._poll(st)
        ray_tpu.get([a.reset_step.remote(0)
                     for g in self.groups for a in g.members], timeout=60)
        return sum(st.losses.values()) / len(st.losses)

    def stage_idents(self) -> List[List[dict]]:
        return [list(grp.idents) for grp in self.groups]

    def stage_stats(self) -> List[List[dict]]:
        """Per-gang, per-member runtime stats (ops, busy/idle, receive-
        window peaks/hits) — the backpressure and overlap observables."""
        return [ray_tpu.get([a.stats.remote() for a in grp.members],
                            timeout=30) for grp in self.groups]

    def shutdown(self):
        for grp in self.groups:
            try:
                grp.shutdown()
            except Exception:
                pass
        self.groups = []
