"""`ray_tpu` command-line interface.

Reference parity: python/ray/scripts/scripts.py (start:529, stop:1013,
status:1955, memory:1905) and the state CLI (`ray list`, `ray summary`,
experimental/state/state_cli.py).

Usage:
    python -m ray_tpu.scripts.cli start --head [--num-cpus N]
    python -m ray_tpu.scripts.cli start --address GCS_ADDR
    python -m ray_tpu.scripts.cli status  --address GCS_ADDR
    python -m ray_tpu.scripts.cli list {nodes,actors,workers,placement-groups,objects} --address GCS_ADDR
    python -m ray_tpu.scripts.cli memory --address GCS_ADDR
    python -m ray_tpu.scripts.cli stop   --address GCS_ADDR
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _fmt_table(rows, columns) -> str:
    if not rows:
        return "(none)"
    widths = [max(len(str(c)), max(len(str(r.get(c, ""))) for r in rows))
              for c in columns]
    lines = ["  ".join(str(c).ljust(w) for c, w in zip(columns, widths))]
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(
            str(r.get(c, "")).ljust(w) for c, w in zip(columns, widths)))
    return "\n".join(lines)


def cmd_start(args) -> int:
    from ray_tpu._private import node as node_mod
    if args.head:
        session_dir = node_mod.new_session_dir()
        group = node_mod.ProcessGroup()
        gcs_address = node_mod.start_gcs(session_dir, group,
                                         port=args.gcs_port)
        node_mod.start_hostd(
            gcs_address, session_dir, group, num_cpus=args.num_cpus,
            num_tpus=args.num_tpus, head=True,
            store_capacity=args.object_store_memory)
        print(f"GCS address: {gcs_address}")
        print(f"Session dir: {session_dir}")
        print(f"Connect with ray_tpu.init(address={gcs_address!r}) or "
              f"join nodes with: python -m ray_tpu.scripts.cli start "
              f"--address {gcs_address}")
        if args.block:
            try:
                group.wait()
            except KeyboardInterrupt:
                group.reap()
        return 0
    if not args.address:
        print("either --head or --address is required", file=sys.stderr)
        return 2
    session_dir = node_mod.new_session_dir()
    group = node_mod.ProcessGroup()
    info = node_mod.start_hostd(
        args.address, session_dir, group, num_cpus=args.num_cpus,
        num_tpus=args.num_tpus, head=False,
        store_capacity=args.object_store_memory)
    print(f"Node started, daemon at {info['address']} "
          f"(node {info['node_id'][:12]})")
    if args.block:
        try:
            group.wait()
        except KeyboardInterrupt:
            group.reap()
    return 0


def cmd_stop(args) -> int:
    from ray_tpu._private.rpc import RpcClient

    async def stop():
        client = RpcClient(args.address)
        try:
            await client.call("Gcs", "shutdown_cluster", {}, timeout=10)
        finally:
            await client.close()

    asyncio.run(stop())
    print("cluster shutdown requested")
    return 0


def cmd_status(args) -> int:
    from ray_tpu import state
    s = state.summarize_cluster(args.address)
    if args.json:
        print(json.dumps(s, indent=2))
        return 0
    print(f"Nodes: {s['nodes_alive']} alive, {s['nodes_dead']} dead")
    print("Resources:")
    for k, total in sorted(s["resources_total"].items()):
        avail = s["resources_available"].get(k, 0.0)
        print(f"  {k}: {total - avail:g}/{total:g} used")
    print(f"Actors: " + (", ".join(
        f"{n} {st}" for st, n in sorted(s["actors"].items())) or "none"))
    print(f"Placement groups: {s['placement_groups']}")
    return 0


def cmd_list(args) -> int:
    from ray_tpu import state
    kind = args.kind.replace("-", "_")
    fn = {
        "nodes": (state.list_nodes,
                  ["node_id", "address", "alive", "is_head",
                   "resources_total"]),
        "actors": (state.list_actors,
                   ["actor_id", "class_name", "state", "name", "node_id",
                    "num_restarts"]),
        "workers": (state.list_workers,
                    ["node_id", "pid", "state", "job_id", "actor_id",
                     "idle_s"]),
        "placement_groups": (state.list_placement_groups,
                             ["placement_group_id", "state", "strategy",
                              "bundles"]),
        "objects": (state.list_objects, None),
        "tasks": (state.list_tasks,
                  ["name", "node_id", "pid", "start", "end"]),
    }.get(kind)
    if fn is None:
        print(f"unknown kind {args.kind!r}", file=sys.stderr)
        return 2
    rows = fn[0](args.address)
    if args.json or fn[1] is None:
        print(json.dumps(rows, indent=2, default=str))
        return 0
    for r in rows:  # truncate ids for table form
        for key in ("node_id", "actor_id", "placement_group_id"):
            if isinstance(r.get(key), str) and len(r[key]) > 12:
                r[key] = r[key][:12]
    print(_fmt_table(rows, fn[1]))
    return 0


def cmd_client_server(args) -> int:
    """Run a thin-client server attached to the cluster (reference:
    `ray start --ray-client-server-port`)."""
    from ray_tpu.util.client.server import serve_forever
    serve_forever(args.address, args.host, args.port)
    return 0


def cmd_dashboard(args) -> int:
    """Run the dashboard head (REST + web UI).  Reference: dashboard.py."""
    from ray_tpu.dashboard.head import main as dash_main
    return dash_main(["--address", args.address, "--host", args.host,
                      "--port", str(args.port)])


def cmd_job(args) -> int:
    """Job submission CLI over the dashboard REST API (reference:
    dashboard/modules/job/cli.py — `ray job submit/list/status/logs/stop`)."""
    from ray_tpu.dashboard.sdk import JobSubmissionClient
    client = JobSubmissionClient(args.dashboard_address)
    if args.job_cmd == "submit":
        runtime_env = {}
        if args.working_dir:
            runtime_env["working_dir"] = args.working_dir
        import shlex
        sub_id = client.submit_job(
            entrypoint=shlex.join(args.entrypoint),
            runtime_env=runtime_env or None,
            submission_id=args.submission_id)
        print(f"submitted: {sub_id}")
        if not args.no_wait:
            rec = client.wait_until_finished(sub_id, timeout=args.timeout)
            print(f"status: {rec['status']}"
                  + (f" ({rec['message']})" if rec.get("message") else ""))
            print(client.get_job_logs(sub_id), end="")
            return 0 if rec["status"] == "SUCCEEDED" else 1
        return 0
    if args.job_cmd == "list":
        rows = [{"submission_id": r["submission_id"], "status": r["status"],
                 "entrypoint": r["entrypoint"][:60]}
                for r in client.list_jobs()]
        print(_fmt_table(rows, ["submission_id", "status", "entrypoint"]))
        return 0
    if args.job_cmd == "status":
        print(json.dumps(client.get_job_status(args.submission_id),
                         indent=2, default=str))
        return 0
    if args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
        return 0
    if args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.submission_id)
              else "not running")
        return 0
    return 2


def cmd_serve(args) -> int:
    """Serve control subcommands (reference: serve CLI scripts.py —
    deploy from a config file, status, shutdown)."""
    import json as jsonlib

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(address=args.address)
    try:
        if args.serve_cmd == "status":
            try:
                print(jsonlib.dumps(serve.status(), indent=2))
            except ValueError:
                print("serve is not running on this cluster")
            return 0
        if args.serve_cmd == "shutdown":
            serve.shutdown()
            print("serve shutdown complete")
            return 0
        if args.serve_cmd == "deploy":
            if not args.config:
                print("serve deploy requires a config file", file=sys.stderr)
                return 2
            # Config schema (reference: serve/schema.py, JSON or YAML):
            # {"applications": [{"import_path": "module:app",
            #                    "deployments": [{"name": ...,
            #                                     "num_replicas": ...}]}]}
            import importlib
            import os
            import sys as _sys
            _sys.path.insert(0, os.getcwd())
            with open(args.config) as f:
                text = f.read()
            try:
                cfg = jsonlib.loads(text)
            except jsonlib.JSONDecodeError:
                import yaml
                cfg = yaml.safe_load(text)
            if not isinstance(cfg, dict):
                print(f"invalid serve config {args.config!r}",
                      file=sys.stderr)
                return 2
            serve.start()
            for app_cfg in cfg.get("applications", []):
                mod_name, _, attr = app_cfg["import_path"].partition(":")
                app = getattr(importlib.import_module(mod_name), attr)
                overrides = {d["name"]: d
                             for d in app_cfg.get("deployments", [])}

                def apply(a):
                    for sub in list(a.args) + list(a.kwargs.values()):
                        if type(sub).__name__ == "Application":
                            apply(sub)
                    o = overrides.get(a.deployment.name)
                    if o:
                        for k in ("num_replicas", "max_concurrent_queries",
                                  "user_config"):
                            if k in o:
                                setattr(a.deployment._config, k, o[k])
                apply(app)
                serve.run(app)
                print(f"deployed application from "
                      f"{app_cfg['import_path']}")
            print(jsonlib.dumps(serve.status(), indent=2))
            return 0
        return 2
    finally:
        ray_tpu.shutdown()


def cmd_metrics(args) -> int:
    from ray_tpu import state
    if getattr(args, "json", False):
        # Structured snapshot (per-node registries, un-merged) for
        # scripting; the default stays Prometheus exposition text.
        print(json.dumps(state.cluster_metrics(args.address), indent=2,
                         default=str))
        return 0
    print(state.prometheus_metrics(args.address), end="")
    return 0


def cmd_trace(args) -> int:
    """ASCII span tree of one trace: every process's begin/end pairs,
    clock-normalized and parent-linked, torn spans flagged (a crash dump
    terminates its open spans at dump time)."""
    from ray_tpu import state
    tree = state.spans(args.trace_id, args.address, since=args.since)
    if args.json:
        print(json.dumps(tree, indent=2, default=str))
        return 0
    root = tree["root"]
    if root is None:
        print(f"no spans for trace {args.trace_id}")
        return 1

    def fmt(n) -> str:
        dur = (f"{n['dur'] * 1e3:9.2f}ms" if n.get("dur") is not None
               else "        ?ms")
        flags = "".join([" TORN" if n.get("torn") else "",
                         " ~trunc" if n.get("truncated") else ""])
        where = (f" [{str(n.get('node_id') or '')[:8]}:{n.get('pid', '?')}]"
                 if n.get("pid") else "")
        payload = n.get("payload") or {}
        extras = " ".join(f"{k}={v}" for k, v in payload.items()
                          if k not in ("ph", "parent", "dur"))
        return (f"{n['plane']}/{n['kind']:<12s} {dur}{flags}{where}"
                + (f" {extras}" if extras else ""))

    def walk(n, prefix: str, is_last: bool, is_root: bool):
        if is_root:
            print(fmt(n))
            child_prefix = ""
        else:
            print(f"{prefix}{'└─ ' if is_last else '├─ '}{fmt(n)}")
            child_prefix = prefix + ("   " if is_last else "│  ")
        kids = sorted(n.get("children", []),
                      key=lambda c: c.get("start") or 0.0)
        for i, c in enumerate(kids):
            walk(c, child_prefix, i == len(kids) - 1, False)

    wall = ((root["end"] - root["start"]) * 1e3
            if root.get("end") is not None and root.get("start") is not None
            else 0.0)
    print(f"trace {args.trace_id[:16]}  wall={wall:.2f}ms  "
          f"{len(tree['spans'])} spans  {tree['torn']} torn")
    walk(root, "", True, True)
    cp = state.critical_path(args.trace_id, args.address, since=args.since)
    if cp["by_kind"]:
        print("critical path:")
        for k, v in cp["by_kind"].items():
            if v * 1e3 < 0.005:
                continue  # zero-length bookkeeping segments
            frac = v / cp["wall"] if cp["wall"] else 0.0
            print(f"  {k:<22s} {v * 1e3:9.2f}ms  {frac:6.1%}")
    return 0


def cmd_analyze(args) -> int:
    """Ranked per-phase latency table: where cluster wall clock goes,
    per span kind (p50/p95/p99, total, fraction of the observed
    window)."""
    from ray_tpu import state
    bd = state.latency_breakdown(args.address, plane=args.plane,
                                 trace_id=args.trace, since=args.since)
    if args.json:
        print(json.dumps(bd, indent=2, default=str))
        return 0
    if not bd["phases"]:
        print("no span data (is RAY_TPU_EVENTS on? did anything run "
              "under a trace?)")
        return 1
    print(f"-- latency breakdown (window {bd['wall']:.3f}s) --")
    print(f"{'phase':<24s} {'count':>7s} {'p50(ms)':>9s} {'p95(ms)':>9s} "
          f"{'p99(ms)':>9s} {'total(s)':>9s} {'%wall':>7s}")
    for ph in bd["phases"]:
        print(f"{ph['plane'] + '/' + ph['kind']:<24s} {ph['count']:>7d} "
              f"{ph['p50'] * 1e3:>9.2f} {ph['p95'] * 1e3:>9.2f} "
              f"{ph['p99'] * 1e3:>9.2f} {ph['total']:>9.3f} "
              f"{ph['fraction']:>7.1%}")
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu import state
    evs = state.timeline(args.address,
                         include_events=getattr(args, "events", False))
    out = getattr(args, "out", None) or "ray_tpu_timeline.json"
    with open(out, "w") as f:
        # Event payloads are free-form; stringify anything exotic rather
        # than losing the whole trace to one unserializable field.
        json.dump(evs, f, default=str)
    print(f"wrote {len(evs)} events to {out} "
          f"(open in chrome://tracing or perfetto)")
    return 0


def cmd_events(args) -> int:
    """Cluster-wide flight-recorder stream: live rings + crash dumps,
    skew-normalized and merged (reference: `ray list cluster-events` /
    experimental/state — here backed by util/events.py)."""
    from ray_tpu import state
    evs = state.events(args.address, plane=args.plane, kind=args.kind,
                       trace_id=args.trace, since=args.since)
    if args.limit:
        evs = evs[-args.limit:]
    if args.json:
        print(json.dumps(evs, indent=2, default=str))
        return 0
    for e in evs:
        ts = e.get("ts_adj", e["ts"])
        trace = e.get("trace_id") or ""
        payload = e.get("payload") or {}
        crash = (f" !{e.get('reason', 'crash')}"
                 if e.get("source") == "crash" else "")
        where = f"{str(e.get('node_id', ''))[:8]}:{e.get('pid', '?')}"
        print(f"{ts:.6f} [{where}{crash}] "
              f"{e.get('plane', ''):<6s} {e.get('kind', ''):<20s}"
              + (f" trace={trace[:8]}" if trace else "")
              + ("".join(f" {k}={v}" for k, v in payload.items())
                 if isinstance(payload, dict) else f" {payload}"))
    print(f"({len(evs)} events)")
    return 0


def cmd_top(args) -> int:
    """Live view: per-plane flight-recorder event rates plus latency
    percentiles from every histogram in the cluster scrape (reference:
    `ray status -v` refresh loop; percentile math in util/metrics.py)."""
    import time as _time

    from ray_tpu import state
    from ray_tpu.util import metrics as mt

    def render() -> str:
        now = _time.time()
        evs = state.events(args.address, since=now - args.window)
        rates = {}
        for e in evs:
            rates[e.get("plane", "?")] = rates.get(e.get("plane", "?"), 0) + 1
        snap = state.cluster_metrics(args.address)
        merged = {}
        mt.merge_snapshot(merged, snap["gcs"])
        for m in snap["nodes"].values():
            mt.merge_snapshot(merged, m)
        lines = [f"-- ray_tpu top (window {args.window:g}s, "
                 f"{len(evs)} events) --",
                 "events/s by plane:"]
        for pl in sorted(rates):
            lines.append(f"  {pl:<8s} {rates[pl] / args.window:10.1f}/s")
        if not rates:
            lines.append("  (none)")
        lines.append("latency percentiles:")
        shown = 0
        for name, entry in sorted(merged.items()):
            if entry.get("type") != "histogram":
                continue
            for series in entry.get("series", []):
                q = mt.series_quantiles(entry, series)
                if q is None:
                    continue
                tags = ",".join(f"{k}={v}" for k, v
                                in sorted(series["tags"].items()))
                label = name + ("{" + tags + "}" if tags else "")
                n = series["value"].get("count", 0)
                lines.append(f"  {label} n={n}"
                             f" p50={q[0.5]:.4g} p95={q[0.95]:.4g}"
                             f" p99={q[0.99]:.4g}")
                shown += 1
        if not shown:
            lines.append("  (no histogram data yet)")
        return "\n".join(lines)

    watch = getattr(args, "watch", None)
    interval = watch if watch else args.interval
    i = 0
    try:
        while True:
            if i:
                _time.sleep(interval)
            if watch:
                # Clear + home, full-screen redraw (watch(1)-style).
                print("\x1b[2J\x1b[H", end="")
            print(render(), flush=True)
            i += 1
            if args.count and i >= args.count:
                break
    except KeyboardInterrupt:
        print()  # leave the shell prompt on its own line
    return 0


def cmd_stack(args) -> int:
    """Dump live thread stacks cluster-wide (reference: `ray stack`)."""
    from ray_tpu import state
    per_node = state.stack_traces(args.address)
    if args.json:
        print(json.dumps(per_node, indent=2, default=str))
        return 0
    for node_id, reply in per_node.items():
        print(f"=== node {node_id[:12]} ===")
        if "error" in reply:
            print(f"  unreachable: {reply['error']}")
            continue
        for proc in reply["processes"]:
            state_txt = proc.get("state", "")
            print(f"-- pid {proc['pid']} ({proc['kind']}"
                  f"{' ' + state_txt if state_txt else ''}) --")
            if proc.get("error"):
                print(f"   <no dump: {proc['error']}>")
            for th in proc["threads"]:
                print(f"  thread {th['name']} ({th['thread_id']}):")
                for line in th["stack"].rstrip().splitlines():
                    print(f"    {line}")
    return 0


def cmd_memory(args) -> int:
    from ray_tpu import state
    rows = [r for r in state.list_objects(args.address) if "capacity" in r]
    for r in rows:
        r["node_id"] = r["node_id"][:12]
        r["used_mb"] = round(r.pop("used", 0) / 1e6, 1)
        r["capacity_mb"] = round(r.pop("capacity", 0) / 1e6, 1)
    print(_fmt_table(rows, ["node_id", "used_mb", "capacity_mb",
                            "num_objects", "num_evictions"]))
    return 0


def cmd_local_dump(args) -> int:
    """Collect this host's session logs + cluster state into a tarball
    (reference: scripts.py local_dump — the ops artifact attached to bug
    reports)."""
    import glob
    import json as _json
    import os
    import tarfile
    import tempfile
    import time as _time

    out = args.out or f"ray_tpu_dump_{int(_time.time())}.tar.gz"
    if args.session_dir:
        sessions = [args.session_dir]
    else:
        if args.sessions <= 0:
            print("--sessions must be >= 1", file=sys.stderr)
            return 2

        def _mtime(p):  # a session dir can vanish between glob and sort
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

        sessions = sorted(glob.glob(os.path.join(
            tempfile.gettempdir(), "ray_tpu", "session_*")), key=_mtime)
        sessions = sessions[-args.sessions:]
    with tarfile.open(out, "w:gz") as tar:
        for sess in sessions:
            logs = os.path.join(sess, "logs")
            if os.path.isdir(logs):
                tar.add(logs, arcname=os.path.join(
                    os.path.basename(sess), "logs"))
        if args.address:
            try:
                from ray_tpu import state
                snap = {
                    "nodes": state.list_nodes(args.address),
                    "actors": state.list_actors(args.address),
                    "workers": state.list_workers(args.address),
                    "summary": state.summarize_cluster(args.address),
                }
                blob = _json.dumps(snap, indent=2, default=str).encode()
                import io as _io
                info = tarfile.TarInfo("cluster_state.json")
                info.size = len(blob)
                tar.addfile(info, _io.BytesIO(blob))
            except Exception as e:  # noqa: BLE001
                print(f"warning: no cluster state captured: {e}",
                      file=sys.stderr)
    print(f"wrote {out} ({len(sessions)} session(s))")
    return 0


def cmd_global_gc(args) -> int:
    """Trigger gc.collect() in every worker in the cluster (reference:
    scripts.py global_gc / ray._private.internal_api.global_gc): frees
    cyclic garbage holding ObjectRefs so their objects can release."""
    import ray_tpu
    ray_tpu.init(address=args.address)

    @ray_tpu.remote(num_cpus=0)
    def _gc():
        import gc
        import os
        return os.getpid(), gc.collect()

    try:
        from ray_tpu import state
        workers = [w for w in state.list_workers(args.address)
                   if w.get("alive")]
        # Best effort: tasks land wherever the scheduler places them, so
        # over-subscribe and report the DISTINCT workers actually hit
        # (the reference broadcasts a core-worker RPC instead).
        n = max(4, 2 * len(workers))
        outs = ray_tpu.get([_gc.remote() for _ in range(n)], timeout=120)
        pids = {pid for pid, _ in outs}
        print(f"gc.collect() ran in {len(pids)} worker(s) "
              f"({n} tasks; cycles collected: "
              f"{sum(c for _, c in outs)})")
    finally:
        ray_tpu.shutdown()
    return 0


def cmd_microbenchmark(args) -> int:
    """Core-runtime microbenchmarks (reference: `ray microbenchmark`)."""
    import importlib.util
    import os
    repo_script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "scripts", "microbench.py")
    if not os.path.exists(repo_script):
        print("scripts/microbench.py not found", file=sys.stderr)
        return 2
    spec = importlib.util.spec_from_file_location("microbench", repo_script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()
    return 0


_RLLIB_ALGOS = {
    "PPO": ("ray_tpu.rllib.ppo", "PPOConfig"),
    "APPO": ("ray_tpu.rllib.appo", "APPOConfig"),
    "IMPALA": ("ray_tpu.rllib.impala", "IMPALAConfig"),
    "A2C": ("ray_tpu.rllib.a2c", "A2CConfig"),
    "DQN": ("ray_tpu.rllib.dqn", "DQNConfig"),
    "SAC": ("ray_tpu.rllib.sac", "SACConfig"),
    "TD3": ("ray_tpu.rllib.td3", "TD3Config"),
    "ES": ("ray_tpu.rllib.es", "ESConfig"),
    "ARS": ("ray_tpu.rllib.ars", "ARSConfig"),
    "LinUCB": ("ray_tpu.rllib.bandit", "LinUCBConfig"),
    "LinTS": ("ray_tpu.rllib.bandit", "LinTSConfig"),
}


def cmd_rllib_train(args) -> int:
    """Train an algorithm from the command line (reference:
    rllib/train.py — `rllib train --algo PPO --env CartPole-v1`)."""
    import importlib
    import json as _json

    import ray_tpu
    mod_name, cfg_name = _RLLIB_ALGOS[args.algo]
    cfg_cls = getattr(importlib.import_module(mod_name), cfg_name)
    ray_tpu.init()
    cfg = (cfg_cls().environment(args.env)
           .rollouts(num_rollout_workers=args.num_workers)
           .debugging(seed=args.seed))
    if args.config:
        cfg.training(**_json.loads(args.config))
    algo = cfg.build()
    try:
        for i in range(args.stop_iters):
            r = algo.train()
            mean = r.get("episode_reward_mean")
            print(f"iter {r['training_iteration']}: "
                  f"reward_mean={mean:.1f} steps={r['timesteps_total']}")
            if args.stop_reward is not None and mean == mean \
                    and mean >= args.stop_reward:
                print(f"stop-reward {args.stop_reward} reached")
                break
        if args.out:
            ckpt = algo.save()
            ckpt.to_directory(args.out)
            print(f"checkpoint written to {args.out}")
    finally:
        algo.stop()
        ray_tpu.shutdown()
    return 0


def cmd_rllib_evaluate(args) -> int:
    """Greedy-policy evaluation of a saved checkpoint (reference:
    rllib/evaluate.py)."""
    import importlib

    import ray_tpu
    from ray_tpu.air.checkpoint import Checkpoint
    mod_name, cfg_name = _RLLIB_ALGOS[args.algo]
    cfg_cls = getattr(importlib.import_module(mod_name), cfg_name)
    ray_tpu.init()
    cfg = (cfg_cls().environment(args.env)
           .rollouts(num_rollout_workers=0)
           .debugging(seed=args.seed))
    algo = cfg.build()
    try:
        algo.restore(Checkpoint.from_directory(args.checkpoint))
        # Scale the step budget to the request: the default 1000-step
        # cap would silently truncate long-episode envs.
        stats = algo.workers.local_worker.evaluate(
            num_episodes=args.episodes, max_steps=args.episodes * 1000)
        rets = stats["episode_returns"]
        if rets:
            import statistics
            print(f"{len(rets)} episodes: mean={statistics.fmean(rets):.1f} "
                  f"min={min(rets):.1f} max={max(rets):.1f}")
        else:
            print("no episodes completed")
    finally:
        algo.stop()
        ray_tpu.shutdown()
    return 0


def cmd_rllib_evaluate_offline(args) -> int:
    """Off-policy evaluation of a checkpointed policy against logged
    experiences (reference: rllib/offline/estimators — `rllib train
    --evaluate-offline` workflow)."""
    import importlib

    import numpy as np

    import ray_tpu
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.rllib.estimators import ESTIMATORS, fit_fqe
    from ray_tpu.rllib.offline import JsonReader
    mod_name, cfg_name = _RLLIB_ALGOS[args.algo]
    cfg_cls = getattr(importlib.import_module(mod_name), cfg_name)
    ray_tpu.init()
    cfg = (cfg_cls().environment(args.env)
           .rollouts(num_rollout_workers=0)
           .debugging(seed=args.seed))
    algo = cfg.build()
    try:
        algo.restore(Checkpoint.from_directory(args.checkpoint))
        policy = algo.workers.local_worker.policy
        if getattr(policy, "num_actions", 0) == 0:
            print("evaluate-offline requires a discrete-action policy "
                  "(the IS/WIS/DM/DR estimators are categorical)")
            return 2

        def target_probs(obs):
            _a, _z, _v, logits = policy.compute_actions(
                np.asarray(obs), explore=False)
            z = logits - logits.max(-1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(-1, keepdims=True)

        batch = JsonReader(args.data).read_all()
        names = [n.strip() for n in args.estimators.split(",") if n.strip()]
        q_fn = None
        if any(n in ("dm", "dr") for n in names):
            q_fn = fit_fqe(batch, target_probs,
                           num_actions=policy.num_actions,
                           gamma=args.gamma, seed=args.seed)
        for name in names:
            cls = ESTIMATORS[name]
            out = cls(target_probs, gamma=args.gamma,
                      q_fn=q_fn).estimate(batch)
            print(f"{name:4s} v_target={out['v_target']:.3f} "
                  f"v_behavior={out['v_behavior']:.3f} "
                  f"v_gain={out['v_gain']:+.3f} "
                  f"({out['episodes']} episodes)")
    finally:
        algo.stop()
        ray_tpu.shutdown()
    return 0


def cmd_up(args) -> int:
    from ray_tpu.autoscaler import launcher
    state = launcher.create_or_update_cluster(
        args.config, no_restart=args.no_restart)
    print(f"cluster up; connect with "
          f"ray_tpu.init(address={state['gcs_address']!r})")
    return 0


def cmd_down(args) -> int:
    from ray_tpu.autoscaler import launcher
    launcher.teardown_cluster(args.config)
    return 0


def cmd_exec(args) -> int:
    from ray_tpu.autoscaler import launcher
    return launcher.exec_cluster(args.config, args.command)


def cmd_submit(args) -> int:
    from ray_tpu.autoscaler import launcher
    return launcher.submit(args.config, args.script, args.script_args)


def cmd_attach(args) -> int:
    import os as _os
    from ray_tpu.autoscaler import launcher
    argv = launcher.attach_command(args.config)
    _os.execvp(argv[0], argv)  # replaces this process


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head node or join a cluster")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--object-store-memory", type=int, default=256 << 20)
    sp.add_argument("--gcs-port", type=int, default=0,
                    help="fixed GCS port for --head (0 = ephemeral)")
    sp.add_argument("--block", action="store_true",
                    help="stay attached; ctrl-c tears the node down")
    sp.set_defaults(fn=cmd_start)

    for name, fn in (("stop", cmd_stop), ("status", cmd_status),
                     ("memory", cmd_memory), ("metrics", cmd_metrics),
                     ("timeline", cmd_timeline), ("stack", cmd_stack)):
        q = sub.add_parser(name)
        q.add_argument("--address", required=True)
        q.add_argument("--json", action="store_true")
        if name == "timeline":
            q.add_argument("--out", default="ray_tpu_timeline.json")
            q.add_argument("--events", action="store_true",
                           help="merge flight-recorder events as "
                                "instant events")
        q.set_defaults(fn=fn)

    q = sub.add_parser("events",
                       help="cluster-wide flight-recorder event stream")
    q.add_argument("--address", required=True)
    q.add_argument("--plane", default=None,
                   help="filter: sched/object/engine/serve/ckpt/"
                        "ingest/train/proc")
    q.add_argument("--kind", default=None)
    q.add_argument("--trace", default=None,
                   help="join: only events carrying this trace id")
    q.add_argument("--since", type=float, default=0.0,
                   help="unix timestamp lower bound")
    q.add_argument("--limit", type=int, default=0,
                   help="keep only the newest N after filtering")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_events)

    q = sub.add_parser("top", help="live per-plane event rates and "
                                   "latency percentiles")
    q.add_argument("--address", required=True)
    q.add_argument("--window", type=float, default=10.0,
                   help="rate window in seconds")
    q.add_argument("--interval", type=float, default=2.0,
                   help="refresh period")
    q.add_argument("--count", type=int, default=0,
                   help="stop after N refreshes (0 = until ctrl-c)")
    q.add_argument("--watch", type=float, nargs="?", const=2.0,
                   default=None, metavar="SECONDS",
                   help="full-screen refresh every N seconds (clear + "
                        "redraw; ctrl-c exits)")
    q.set_defaults(fn=cmd_top)

    q = sub.add_parser("trace",
                       help="ASCII span tree + critical path of one trace")
    q.add_argument("trace_id")
    q.add_argument("--address", required=True)
    q.add_argument("--since", type=float, default=0.0,
                   help="unix timestamp lower bound for the scrape")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_trace)

    q = sub.add_parser("analyze",
                       help="ranked per-phase latency breakdown from "
                            "span durations")
    q.add_argument("--address", required=True)
    q.add_argument("--plane", default=None,
                   help="narrow to one plane (sched/object/engine/serve/"
                        "ckpt/ingest/train/proc)")
    q.add_argument("--trace", default=None,
                   help="narrow to one trace id")
    q.add_argument("--since", type=float, default=0.0,
                   help="unix timestamp lower bound for the scrape")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_analyze)

    q = sub.add_parser("serve", help="serve control (deploy/status/shutdown)")
    q.add_argument("serve_cmd", choices=["deploy", "status", "shutdown"])
    q.add_argument("config", nargs="?", help="config file for deploy")
    q.add_argument("--address", required=True)
    q.set_defaults(fn=cmd_serve)

    q = sub.add_parser("client-server",
                       help="serve thin clients (ray_tpu:// mode)")
    q.add_argument("--address", required=True)
    q.add_argument("--port", type=int, default=10001)
    q.add_argument("--host", default="0.0.0.0")
    q.set_defaults(fn=cmd_client_server)

    q = sub.add_parser("dashboard", help="run the dashboard head "
                                         "(REST API + web UI)")
    q.add_argument("--address", required=True)
    q.add_argument("--host", default="127.0.0.1")
    q.add_argument("--port", type=int, default=8265)
    q.set_defaults(fn=cmd_dashboard)

    q = sub.add_parser("job", help="submit and manage jobs")
    jsub = q.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--dashboard-address", required=True)
    js.add_argument("--working-dir")
    js.add_argument("--submission-id")
    js.add_argument("--no-wait", action="store_true")
    js.add_argument("--timeout", type=float, default=600.0)
    js.add_argument("entrypoint", nargs="+")
    js.set_defaults(fn=cmd_job)
    for jname in ("list", "status", "logs", "stop"):
        js = jsub.add_parser(jname)
        js.add_argument("--dashboard-address", required=True)
        if jname != "list":
            js.add_argument("submission_id")
        js.set_defaults(fn=cmd_job)

    q = sub.add_parser("list", help="list live cluster entities")
    q.add_argument("kind", choices=["nodes", "actors", "workers",
                                    "placement-groups", "objects",
                                    "tasks"])
    q.add_argument("--address", required=True)
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_list)

    # Cluster launcher (reference: ray up/down/exec/submit/attach,
    # scripts.py:1247) over the CommandRunner plane.
    q = sub.add_parser("up", help="start a cluster from a config file")
    q.add_argument("config")
    q.add_argument("--no-restart", action="store_true")
    q.set_defaults(fn=cmd_up)
    q = sub.add_parser("down", help="tear a launched cluster down")
    q.add_argument("config")
    q.set_defaults(fn=cmd_down)
    q = sub.add_parser("exec", help="run a command on the cluster head")
    q.add_argument("config")
    q.add_argument("command")
    q.set_defaults(fn=cmd_exec)
    q = sub.add_parser("submit", help="ship a script to the head and run it")
    q.add_argument("config")
    q.add_argument("script")
    q.add_argument("script_args", nargs="*")
    q.set_defaults(fn=cmd_submit)
    q = sub.add_parser("attach", help="interactive shell on the head")
    q.add_argument("config")
    q.set_defaults(fn=cmd_attach)

    q = sub.add_parser("local-dump",
                       help="tar up session logs + cluster state")
    q.add_argument("--address", default=None)
    q.add_argument("--out", default=None)
    q.add_argument("--sessions", type=int, default=1,
                   help="how many recent sessions to include")
    q.add_argument("--session-dir", default=None,
                   help="dump exactly this session directory")
    q.set_defaults(fn=cmd_local_dump)
    q = sub.add_parser("global-gc",
                       help="run gc.collect() across the cluster")
    q.add_argument("--address", required=True)
    q.set_defaults(fn=cmd_global_gc)
    q = sub.add_parser("microbenchmark",
                       help="core-runtime microbenchmarks")
    q.set_defaults(fn=cmd_microbenchmark)

    q = sub.add_parser("rllib", help="train/evaluate RL algorithms")
    rsub = q.add_subparsers(dest="rllib_cmd", required=True)
    rt = rsub.add_parser("train")
    rt.add_argument("--algo", choices=sorted(_RLLIB_ALGOS), default="PPO")
    rt.add_argument("--env", default="CartPole-v1")
    rt.add_argument("--num-workers", type=int, default=1)
    rt.add_argument("--stop-iters", type=int, default=50)
    rt.add_argument("--stop-reward", type=float, default=None)
    rt.add_argument("--seed", type=int, default=0)
    rt.add_argument("--config", default=None,
                    help="JSON of extra .training(...) overrides")
    rt.add_argument("--out", default=None,
                    help="write a checkpoint directory on finish")
    rt.set_defaults(fn=cmd_rllib_train)
    re_ = rsub.add_parser("evaluate")
    re_.add_argument("checkpoint")
    re_.add_argument("--algo", choices=sorted(_RLLIB_ALGOS), default="PPO")
    re_.add_argument("--env", default="CartPole-v1")
    re_.add_argument("--episodes", type=int, default=10)
    re_.add_argument("--seed", type=int, default=0)
    re_.set_defaults(fn=cmd_rllib_evaluate)
    ro = rsub.add_parser(
        "evaluate-offline",
        help="off-policy estimates of a checkpointed policy on logged "
             "data (reference: rllib/offline/estimators)")
    ro.add_argument("checkpoint")
    ro.add_argument("--data", required=True,
                    help="JSON experience directory (JsonWriter output)")
    ro.add_argument("--algo", choices=sorted(_RLLIB_ALGOS), default="PPO")
    ro.add_argument("--env", default="CartPole-v1")
    ro.add_argument("--estimators", default="is,wis,dm,dr")
    ro.add_argument("--gamma", type=float, default=0.99)
    ro.add_argument("--seed", type=int, default=0)
    ro.set_defaults(fn=cmd_rllib_evaluate_offline)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
