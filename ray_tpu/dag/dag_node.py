"""Lazy DAG nodes over tasks and actors.

Reference parity: python/ray/dag/dag_node.py:23 (DAGNode),
function_node.py, class_node.py, input_node.py.  `fn.bind(x)` builds the
graph without executing; `node.execute(input)` resolves it: every node
becomes one task/actor call whose upstream arguments are passed as
ObjectRefs (no intermediate driver materialization).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Tuple


class DAGNode:
    """Base: holds bound args and resolves upstream nodes on execute."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._uuid = uuid.uuid4().hex

    # -- traversal ---------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, cache: Dict[str, Any], input_value) -> Tuple:
        args = tuple(
            a._execute_cached(cache, input_value) if isinstance(a, DAGNode)
            else a
            for a in self._bound_args)
        kwargs = {
            k: (v._execute_cached(cache, input_value)
                if isinstance(v, DAGNode) else v)
            for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_cached(self, cache: Dict[str, Any], input_value):
        if self._uuid not in cache:
            cache[self._uuid] = self._execute_impl(cache, input_value)
        return cache[self._uuid]

    def _execute_impl(self, cache, input_value):
        raise NotImplementedError

    def execute(self, input_value: Any = None):
        """Run the DAG; returns the root's ObjectRef (or actor handle for
        a ClassNode root).  Shared upstream nodes execute once."""
        return self._execute_cached({}, input_value)


class InputNode(DAGNode):
    """Placeholder for the runtime input (reference: input_node.py).
    Usable as a context manager for reference-API parity:
        with InputNode() as inp: dag = f.bind(inp)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, cache, input_value):
        return input_value


class FunctionNode(DAGNode):
    """A bound remote function call (reference: function_node.py)."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor construction (reference: class_node.py).  Method
    calls on the node create ClassMethodNodes."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethod(self, name)

    def _execute_impl(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        return self._actor_cls.remote(*args, **kwargs)


class _UnboundMethod:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    """A bound method call on a ClassNode's actor."""

    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _children(self) -> List[DAGNode]:
        return [self._class_node] + super()._children()

    def _execute_impl(self, cache, input_value):
        actor = self._class_node._execute_cached(cache, input_value)
        args, kwargs = self._resolve_args(cache, input_value)
        return getattr(actor, self._method).remote(*args, **kwargs)
