"""ray_tpu.dag — lazy task/actor graph authoring via .bind().

Reference parity: python/ray/dag/ (DAGNode dag_node.py:23, function/class
nodes, InputNode); consumed by Serve graphs and Workflow.
"""

from ray_tpu.dag.dag_node import (  # noqa: F401
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
)

__all__ = ["ClassMethodNode", "ClassNode", "DAGNode", "FunctionNode",
           "InputNode"]
