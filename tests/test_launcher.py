"""Cluster launcher tests (reference: ray up/down/exec/submit,
scripts.py:1247 + autoscaler/_private/command_runner.py) — local provider
end to end: up starts a real head + a joined worker node, exec/submit run
against it, down stops everything."""

import json
import os
import subprocess
import sys
import time

import pytest


@pytest.fixture
def config_path(tmp_path, monkeypatch):
    # Keep launcher state out of the real home dir.
    monkeypatch.setattr(
        "ray_tpu.autoscaler.launcher._STATE_DIR", str(tmp_path / "state"))
    cfg = {
        "cluster_name": f"t{os.getpid()}",
        "provider": {
            "type": "local",
            "head_ip": "127.0.0.1",
            "worker_ips": ["127.0.0.1"],
            "gcs_port": 46412,
        },
        "head_options": "--num-cpus 2",
        "worker_options": "--num-cpus 2",
        "python": sys.executable,
        # The repo isn't pip-installed in CI; a real deployment would put
        # this in setup_commands instead.
        "env": {"PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))},
    }
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps(cfg))
    yield str(p)
    from ray_tpu.autoscaler import launcher
    try:
        launcher.teardown_cluster(str(p))
    except Exception:
        pass
    time.sleep(1.0)


def test_up_exec_submit_down(config_path, tmp_path):
    from ray_tpu import state as st
    from ray_tpu.autoscaler import launcher

    cluster = launcher.create_or_update_cluster(config_path)
    addr = cluster["gcs_address"]
    assert addr.endswith(":46412")

    # Both the head node and the joined worker node are alive.
    deadline = time.time() + 60
    while time.time() < deadline:
        alive = [n for n in st.list_nodes(addr) if n["alive"]]
        if len(alive) >= 2:
            break
        time.sleep(0.5)
    assert len(alive) >= 2

    # exec: command runs with RAY_TPU_ADDRESS pointing at the cluster.
    rc = launcher.exec_cluster(config_path, "echo addr=$RAY_TPU_ADDRESS")
    assert rc == 0

    # submit: a driver script connects and runs a task on the cluster.
    script = tmp_path / "drv.py"
    script.write_text(
        "import os, ray_tpu\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def f(): return 'from cluster'\n"
        "assert ray_tpu.get(f.remote()).endswith('cluster')\n"
        "print('submit-ok')\n"
        "ray_tpu.shutdown()\n")
    rc = launcher.submit(config_path, str(script), timeout=120)
    assert rc == 0

    launcher.teardown_cluster(config_path)
    # GCS is gone; the state record too.
    assert launcher.load_state(json.loads(
        open(config_path).read())["cluster_name"]) is None
    deadline = time.time() + 20
    while time.time() < deadline:
        if not launcher._alive(addr):
            break
        time.sleep(0.5)
    assert not launcher._alive(addr)


def test_ssh_command_runner_argv_construction():
    """No sshd in the CI image, so pin the ssh/scp argv the runner
    builds (reference: command_runner.py SSHCommandRunner options incl.
    ControlMaster multiplexing)."""
    from ray_tpu.autoscaler.command_runner import SSHCommandRunner

    r = SSHCommandRunner("10.0.0.9", user="ubuntu",
                         key_path="~/.ssh/k.pem", port=2222)
    base = r._base()
    assert base[0] == "ssh"
    assert "-o" in base and "StrictHostKeyChecking=no" in base
    assert "ControlMaster=auto" in base
    i = base.index("-i")
    assert base[i + 1].endswith("/.ssh/k.pem")  # ~ expanded
    assert base[base.index("-p") + 1] == "2222"
    assert r._target() == "ubuntu@10.0.0.9"

    scp = r._base(scp=True)
    assert scp[0] == "scp" and scp[scp.index("-P") + 1] == "2222"

    # run() env vars are shell-quoted ahead of the command.
    import unittest.mock as mock
    with mock.patch("subprocess.run") as run:
        run.return_value = mock.Mock(returncode=0, stdout="")
        r.run("echo hi", env={"A": "x y"})
        argv = run.call_args[0][0]
        assert argv[-1] == "A='x y' echo hi"
        assert argv[-2] == "ubuntu@10.0.0.9"

    with mock.patch("subprocess.run") as run:
        run.return_value = mock.Mock(returncode=0)
        r.run_detached("sleep 5", "/tmp/x/log.txt")
        argv = run.call_args[0][0]
        assert "nohup sleep 5 > /tmp/x/log.txt 2>&1 < /dev/null &" \
            in argv[-1]
