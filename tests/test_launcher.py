"""Cluster launcher tests (reference: ray up/down/exec/submit,
scripts.py:1247 + autoscaler/_private/command_runner.py) — local provider
end to end: up starts a real head + a joined worker node, exec/submit run
against it, down stops everything."""

import json
import os
import subprocess
import sys
import time

import pytest


@pytest.fixture
def config_path(tmp_path, monkeypatch):
    # Keep launcher state out of the real home dir.
    monkeypatch.setattr(
        "ray_tpu.autoscaler.launcher._STATE_DIR", str(tmp_path / "state"))
    cfg = {
        "cluster_name": f"t{os.getpid()}",
        "provider": {
            "type": "local",
            "head_ip": "127.0.0.1",
            "worker_ips": ["127.0.0.1"],
            "gcs_port": 46412,
        },
        "head_options": "--num-cpus 2",
        "worker_options": "--num-cpus 2",
        "python": sys.executable,
        # The repo isn't pip-installed in CI; a real deployment would put
        # this in setup_commands instead.
        "env": {"PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))},
    }
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps(cfg))
    yield str(p)
    from ray_tpu.autoscaler import launcher
    try:
        launcher.teardown_cluster(str(p))
    except Exception:
        pass
    time.sleep(1.0)


def test_up_exec_submit_down(config_path, tmp_path):
    from ray_tpu import state as st
    from ray_tpu.autoscaler import launcher

    cluster = launcher.create_or_update_cluster(config_path)
    addr = cluster["gcs_address"]
    assert addr.endswith(":46412")

    # Both the head node and the joined worker node are alive.
    deadline = time.time() + 60
    while time.time() < deadline:
        alive = [n for n in st.list_nodes(addr) if n["alive"]]
        if len(alive) >= 2:
            break
        time.sleep(0.5)
    assert len(alive) >= 2

    # exec: command runs with RAY_TPU_ADDRESS pointing at the cluster.
    rc = launcher.exec_cluster(config_path, "echo addr=$RAY_TPU_ADDRESS")
    assert rc == 0

    # submit: a driver script connects and runs a task on the cluster.
    script = tmp_path / "drv.py"
    script.write_text(
        "import os, ray_tpu\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def f(): return 'from cluster'\n"
        "assert ray_tpu.get(f.remote()).endswith('cluster')\n"
        "print('submit-ok')\n"
        "ray_tpu.shutdown()\n")
    rc = launcher.submit(config_path, str(script), timeout=120)
    assert rc == 0

    launcher.teardown_cluster(config_path)
    # GCS is gone; the state record too.
    assert launcher.load_state(json.loads(
        open(config_path).read())["cluster_name"]) is None
    deadline = time.time() + 20
    while time.time() < deadline:
        if not launcher._alive(addr):
            break
        time.sleep(0.5)
    assert not launcher._alive(addr)
