"""Span tracing + critical-path attribution suite (PR 11).

Covers the ISSUE checklist: begin/end wire format over the event ring,
cross-process span-tree reconstruction tolerant of out-of-order arrival,
torn spans terminated at crash-dump time, ring-overflow truncation,
skew-normalized `since` filtering (the ts_adj regression), the
critical-path walk, and the `cli trace` / `cli analyze` renderings —
including the chaos acceptance run where a killed serve replica's crash
dump is stitched into one trace with its replacement.
"""

import io
import re
import time
from contextlib import redirect_stdout

import pytest

import ray_tpu
from ray_tpu import state
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.util import events, spans, tracing
from ray_tpu.util.events import FlightRecorder


@pytest.fixture(autouse=True)
def _fresh_recorder():
    events.reset()
    yield
    events.reset()
    GLOBAL_CONFIG.invalidate_cache()


def _local_stream():
    """This process's ring as a merged-stream shaped list (ts_adj=ts)."""
    return [dict(e, pid=1, node_id="n1", source="live", ts_adj=e["ts"])
            for e in events.snapshot()]


# ---------------------------------------------------------------------------
# Wire format + pairing
# ---------------------------------------------------------------------------


def test_begin_end_wire_format():
    tok = spans.begin("sched", "submit", ctx=("t1", None), name="f")
    time.sleep(0.01)
    spans.end(tok, status=0)
    snap = events.snapshot(kind="submit")
    assert len(snap) == 2
    b, e = snap
    assert b["payload"]["ph"] == "B" and b["payload"]["name"] == "f"
    assert e["payload"]["ph"] == "E" and e["payload"]["status"] == 0
    assert b["span_id"] == e["span_id"] and b["trace_id"] == "t1"
    assert e["payload"]["dur"] >= 0.01


def test_end_none_token_is_noop():
    spans.end(None)  # recorder off at begin time: must not raise
    assert events.snapshot() == []


def test_disabled_collapses_to_none(monkeypatch):
    monkeypatch.setenv("RAY_TPU_EVENTS", "0")
    GLOBAL_CONFIG.invalidate_cache()
    events.reset()
    assert spans.begin("sched", "submit") is None
    with spans.span("ingest", "h2d") as tok:
        assert tok is None
    assert events.snapshot() == []


def test_span_context_manager_nests():
    with tracing.trace("nest") as tid:
        with spans.span("train", "step", step=1):
            with spans.span("ingest", "h2d"):
                pass
    table, roots = state.build_spans(_local_stream(), tid)
    by_kind = {r["kind"]: r for r in table.values()}
    assert by_kind["h2d"]["parent"] == by_kind["step"]["sid"]
    assert by_kind["step"]["parent"] == by_kind["trace"]["sid"]
    assert len(roots) == 1 and roots[0]["kind"] == "trace"


# ---------------------------------------------------------------------------
# Reconstruction edge cases (synthetic multi-process streams)
# ---------------------------------------------------------------------------


def _ev(ts, plane, kind, tid, sid, payload, pid=1, source="live"):
    return {"ts": ts, "ts_adj": ts, "plane": plane, "kind": kind,
            "trace_id": tid, "span_id": sid, "payload": payload,
            "pid": pid, "node_id": f"n{pid}", "source": source,
            "seq": int(ts * 1e6) % (1 << 30)}


def test_out_of_order_begin_end_across_processes():
    """E before B, child before parent, interleaved pids: fields fill in
    regardless of arrival order."""
    evs = [
        _ev(10.5, "sched", "exec", "t", "w1", {"ph": "E", "dur": 0.4},
            pid=2),
        _ev(10.0, "proc", "trace", "t", "root", {"ph": "B"}, pid=1),
        _ev(10.1, "sched", "exec", "t", "w1",
            {"ph": "B", "parent": "root"}, pid=2),
        _ev(11.0, "proc", "trace", "t", "root", {"ph": "E", "dur": 1.0},
            pid=1),
    ]
    for perm in (evs, evs[::-1], [evs[2], evs[0], evs[3], evs[1]]):
        table, roots = state.build_spans(perm, "t")
        assert len(roots) == 1 and roots[0]["sid"] == "root"
        w = table["w1"]
        assert w["start"] == pytest.approx(10.1)
        assert w["end"] == pytest.approx(10.5)
        assert not w["torn"] and not w["truncated"]
        assert roots[0]["children"] == [w]


def test_missing_end_terminates_at_crash_time():
    """A span whose process crash-dumped ends at the dump's timestamp,
    not at the observation horizon, and is marked torn."""
    evs = [
        _ev(10.0, "proc", "trace", "t", "root", {"ph": "B"}, pid=1),
        _ev(10.2, "engine", "decode", "t", "d1",
            {"ph": "B", "parent": "root"}, pid=9, source="crash"),
        _ev(10.6, "proc", "crash_dump", "t", None, {}, pid=9,
            source="crash"),
        _ev(20.0, "proc", "trace", "t", "root", {"ph": "E", "dur": 10.0},
            pid=1),
    ]
    table, _ = state.build_spans(evs, "t")
    d = table["d1"]
    assert d["torn"]
    assert d["end"] == pytest.approx(10.6)      # crash time, not 20.0
    assert d["dur"] == pytest.approx(0.4)


def test_missing_end_without_dump_uses_horizon():
    evs = [
        _ev(10.0, "sched", "task", "t", "s1", {"ph": "B"}, pid=1),
        _ev(12.5, "proc", "tick", None, None, {}, pid=1),
    ]
    table, _ = state.build_spans(evs, "t")
    assert table["s1"]["torn"]
    assert table["s1"]["end"] == pytest.approx(12.5)


def test_ring_overflow_truncates_span():
    """Overflow evicts the B slot: the span is marked truncated and its
    start is back-dated from the end event's carried duration."""
    events.reset()
    events._recorder = FlightRecorder(capacity=16)
    events._initialized = True
    tok = spans.begin("sched", "task", ctx=("t", None), name="victim")
    time.sleep(0.02)
    for i in range(40):          # flood: the B slot is long gone
        events.record("proc", "tick", i=i)
    spans.end(tok)
    table, roots = state.build_spans(_local_stream(), "t")
    rec = table[tok.sid]
    assert rec["truncated"] and not rec["torn"]
    assert rec["start"] == pytest.approx(rec["end"] - rec["dur"])
    assert rec["dur"] >= 0.02
    assert rec in roots          # orphaned: parentless after overflow


# ---------------------------------------------------------------------------
# ts_adj merge + since regression (two skewed "processes")
# ---------------------------------------------------------------------------


def test_since_applies_to_skew_adjusted_time():
    """A node whose clock runs 100s behind must not leak stale events
    past `since`, and one running ahead must not hide fresh ones.  The
    regression: filtering on raw remote ts did both."""
    now = 1000.0
    # Node A's clock is 100s BEHIND: its events carry ts-100.
    reply_a = {"now": now - 100.0, "events": [
        {"ts": now - 100.0 - 5.0, "plane": "sched", "kind": "old",
         "trace_id": None, "span_id": None, "payload": {}, "pid": 11,
         "seq": 1, "source": "live"},       # really 5s old
        {"ts": now - 100.0 - 0.5, "plane": "sched", "kind": "fresh_a",
         "trace_id": None, "span_id": None, "payload": {}, "pid": 11,
         "seq": 2, "source": "live"},       # really 0.5s old
    ]}
    # Node B's clock is 100s AHEAD.
    reply_b = {"now": now + 100.0, "events": [
        {"ts": now + 100.0 - 0.2, "plane": "sched", "kind": "fresh_b",
         "trace_id": None, "span_id": None, "payload": {}, "pid": 22,
         "seq": 1, "source": "live"},       # really 0.2s old
    ]}
    sa = state._normalize_events_reply(reply_a, "aaaa", now, now)
    sb = state._normalize_events_reply(reply_b, "bbbb", now, now)
    merged = state._merge_event_streams([sa, sb], plane=None, kind=None,
                                        trace_id=None, since=now - 1.0)
    kinds = [e["kind"] for e in merged]
    assert kinds == ["fresh_a", "fresh_b"]   # skew-corrected order
    for e in merged:
        assert e["ts_adj"] >= now - 1.0
    # The adjusted clocks agree to within the RPC round trip (0 here).
    assert merged[0]["ts_adj"] == pytest.approx(now - 0.5)
    assert merged[1]["ts_adj"] == pytest.approx(now - 0.2)


def test_merge_dedups_crash_vs_live_copy():
    """The same (pid, seq) arriving from a live ring and a crash dump
    collapses to one event, preferring the live copy."""
    base = {"ts": 5.0, "ts_adj": 5.0, "plane": "sched", "kind": "k",
            "trace_id": None, "span_id": None, "payload": {}, "pid": 7,
            "seq": 3}
    live = dict(base, source="live")
    crash = dict(base, source="crash")
    merged = state._merge_event_streams(
        [[crash], [live]], plane=None, kind=None, trace_id=None,
        since=0.0)
    assert len(merged) == 1 and merged[0]["source"] == "live"


# ---------------------------------------------------------------------------
# Critical path + breakdown on a live single-node cluster
# ---------------------------------------------------------------------------


@pytest.fixture
def mini_cluster():
    info = ray_tpu.init(num_cpus=2, object_store_memory=64 << 20)
    try:
        yield info
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()


def test_task_trace_critical_path(mini_cluster):
    @ray_tpu.remote
    def f(x):
        time.sleep(0.05)
        return x + 1

    ray_tpu.get(f.remote(0))          # warm the lease pool
    with tracing.trace("cp") as tid:
        ray_tpu.get([f.remote(i) for i in range(3)])
    time.sleep(0.3)
    tree = state.spans(tid)
    kinds = {(s["plane"], s["kind"]) for s in tree["spans"]}
    assert ("sched", "submit") in kinds
    assert ("sched", "exec") in kinds
    assert tree["root"]["kind"] == "trace"
    cp = state.critical_path(tid)
    assert cp["wall"] > 0.05
    # The path must tile the whole wall clock, in order, gap-free.
    segs = cp["segments"]
    assert segs and segs[0]["start"] == pytest.approx(
        tree["root"]["start"], abs=1e-6)
    assert segs[-1]["end"] == pytest.approx(tree["root"]["end"], abs=1e-6)
    for a, b in zip(segs, segs[1:]):
        assert b["start"] == pytest.approx(a["end"], abs=1e-6)
    covered = sum(v for v in cp["by_kind"].values())
    assert covered == pytest.approx(cp["wall"], rel=1e-6)
    # A sleep-bound workload is execution-dominated.  The driver-side
    # inflight span covers the shipped->reply residency (the worker's
    # task span is its *sibling*: trace_ctx is serialized into the push
    # payload at submit time, so the task parents on the trace root),
    # so the backward walk may charge the window to either side; a
    # zero-hop dispatch still covers the round trip itself.
    top = max(cp["by_kind"], key=cp["by_kind"].get)
    assert top in ("sched:exec", "sched:dispatch", "sched:inflight")
    # The per-phase breakdown sees the worker-side span directly and must
    # rank exec as the dominant phase regardless.
    bd = state.latency_breakdown(trace_id=tid)
    execs = [p for p in bd["phases"] if p["kind"] == "exec"]
    assert execs and execs[0]["p50"] >= 0.04


def test_latency_breakdown_fractions(mini_cluster):
    @ray_tpu.remote
    def f():
        time.sleep(0.02)

    with tracing.trace("bd"):
        ray_tpu.get([f.remote() for _ in range(3)])
    time.sleep(0.3)
    bd = state.latency_breakdown()
    phases = {f'{p["plane"]}/{p["kind"]}': p for p in bd["phases"]}
    assert "sched/exec" in phases
    p = phases["sched/exec"]
    assert p["count"] >= 3 and p["p50"] >= 0.02
    assert 0.0 < p["fraction"] <= 1.0 + 1e-9
    assert bd["wall"] > 0.0
    # Root trace scopes are excluded from attribution.
    assert "proc/trace" not in phases


def test_untraced_tasks_emit_no_lifecycle_spans(mini_cluster):
    """The hot path stays span-free without an explicit trace: one None
    check per site, no B/E ring traffic."""
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(5)])
    time.sleep(0.2)
    evs = state.events()
    lifecycle = [e for e in evs
                 if e["kind"] in ("submit", "sched_queue", "dispatch",
                                  "task", "exec", "arg_fetch",
                                  "result_seal")
                 and isinstance(e.get("payload"), dict)
                 and e["payload"].get("ph") in ("B", "E")
                 and e.get("plane") == "sched"]
    assert lifecycle == []


# ---------------------------------------------------------------------------
# Chaos acceptance: killed replica's crash dump stitched into the trace
# ---------------------------------------------------------------------------


@pytest.fixture
def serve_chaos_cluster(request):
    from ray_tpu._private import fault_injection as fi
    cfg = dict(getattr(request, "param", {}))
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    from ray_tpu import serve
    serve.start()
    try:
        yield info
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        from ray_tpu.serve import _private as sp
        with sp._router_states_lock:
            sp._router_states.clear()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


@pytest.mark.chaos
@pytest.mark.parametrize(
    "serve_chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 31,
      "chaos_kill_replica_salts": "*",
      "chaos_kill_replica_at": 4,
      "chaos_max_faults": 1}],
    indirect=True)
def test_chaos_kill_span_tree_stitches_torn_span(serve_chaos_cluster):
    """ISSUE acceptance criterion: `state.critical_path` on a trace that
    includes a chaos-killed serve replica reconstructs the full tree
    across the killed process's crash dump and its replacement — one
    trace id, the torn span marked — and `cli trace` renders it."""
    from ray_tpu import serve
    from ray_tpu.scripts import cli

    handle = serve.run(serve.LLMDeployment.options(
        name="llm_spans").bind(model="gpt", config="nano", max_lanes=4,
                               seed=0))
    with tracing.trace("chaos-spans") as tid:
        got = list(handle.options("generate",
                                  failover=serve.llm_stream_resume)
                   .stream([1, 2, 3], 8))
    assert len(got) == 8

    deadline = time.time() + 20
    tree = {"spans": [], "torn": 0}
    while time.time() < deadline:
        tree = state.spans(tid)
        pids = {s["pid"] for s in tree["spans"] if s["pid"]}
        if tree["torn"] >= 1 and len(pids) >= 2:
            break
        time.sleep(0.5)

    # One trace id spans the killed incarnation AND its replacement.
    pids = {s["pid"] for s in tree["spans"] if s["pid"]}
    assert len(pids) >= 2, f"tree never crossed processes: {tree}"
    torn = [s for s in tree["spans"] if s["torn"]]
    assert torn, "the killed replica's open span was not marked torn"
    # Torn spans were terminated (crash dump or horizon): end is set,
    # so the tree is fully renderable.
    for s in tree["spans"]:
        assert s["end"] is not None and s["start"] is not None
    assert tree["root"] is not None

    cp = state.critical_path(tid)
    assert cp["wall"] > 0 and cp["segments"]
    assert cp["torn"] >= 1

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["trace", tid, "--address",
                       serve_chaos_cluster["gcs_address"]])
    assert rc == 0
    out = buf.getvalue()
    assert "TORN" in out
    assert "critical path:" in out
    # Both engine-side and serve-side phases render in one tree.
    assert re.search(r"engine/(prefill|decode)", out)
    assert "serve/" in out


# ---------------------------------------------------------------------------
# cli analyze
# ---------------------------------------------------------------------------


def test_cli_analyze_renders_table(mini_cluster):
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    def f():
        time.sleep(0.02)

    with tracing.trace("an"):
        ray_tpu.get([f.remote() for _ in range(2)])
    time.sleep(0.3)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["analyze", "--address",
                       mini_cluster["gcs_address"]])
    assert rc == 0
    out = buf.getvalue()
    assert "latency breakdown" in out
    assert "sched/exec" in out
    assert "%wall" in out
