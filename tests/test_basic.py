"""End-to-end core runtime tests (reference: python/ray/tests/test_basic_1.py
and test_actor.py coverage patterns) against a real multi-process cluster."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_put_get(cluster):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_large_numpy(cluster):
    arr = np.random.rand(1 << 20)  # 8 MB -> plasma path
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_simple_task(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    x = ray_tpu.put(10)
    r1 = add.remote(x, 5)
    r2 = add.remote(r1, r1)
    assert ray_tpu.get(r2) == 30


def test_task_large_return(cluster):
    @ray_tpu.remote
    def big():
        return np.ones(1 << 20)

    out = ray_tpu.get(big.remote())
    assert out.sum() == 1 << 20


def test_task_error_propagates(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_parallel_tasks(cluster):
    @ray_tpu.remote
    def square(i):
        return i * i

    refs = [square.remote(i) for i in range(20)]
    assert ray_tpu.get(refs) == [i * i for i in range(20)]


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) * 10

    assert ray_tpu.get(outer.remote(5)) == 60


def test_num_returns(cluster):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_wait(cluster):
    import time

    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    refs = [slow.remote(0.05), slow.remote(2.0)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=1, timeout=10)
    assert len(ready) == 1 and len(not_ready) == 1
    assert ray_tpu.get(ready[0]) == 0.05


def test_actor_basics(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16
    assert ray_tpu.get(c.value.remote()) == 16


def test_actor_ordering(cluster):
    @ray_tpu.remote
    class Accum:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return len(self.log)

        def get_log(self):
            return self.log

    a = Accum.remote()
    for i in range(50):
        a.add.remote(i)
    assert ray_tpu.get(a.get_log.remote()) == list(range(50))


def test_named_actor(cluster):
    @ray_tpu.remote
    class Registry:
        def ping(self):
            return "pong"

    Registry.options(name="reg1").remote()
    h = ray_tpu.get_actor("reg1")
    assert ray_tpu.get(h.ping.remote()) == "pong"


def test_actor_handle_passing(cluster):
    @ray_tpu.remote
    class Sink:
        def __init__(self):
            self.items = []

        def push(self, x):
            self.items.append(x)
            return len(self.items)

        def size(self):
            return len(self.items)

    @ray_tpu.remote
    def producer(sink, n):
        return ray_tpu.get([sink.push.remote(i) for i in range(n)])

    s = Sink.remote()
    ray_tpu.get(producer.remote(s, 5))
    assert ray_tpu.get(s.size.remote()) == 5


def test_kill_actor(cluster):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "ok"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote()) == "ok"
    ray_tpu.kill(v)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(v.ping.remote())


def test_actor_restart_after_crash(cluster):
    import time

    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def call(self):
            self.calls += 1
            return self.calls

        def die(self):
            import os
            os._exit(1)

    p = Phoenix.remote()
    assert ray_tpu.get(p.call.remote()) == 1
    assert ray_tpu.get(p.call.remote()) == 2
    p.die.remote()
    time.sleep(1.0)
    # Restarted instance: fresh state, and calls from the old handle (with
    # advanced seq numbers) must not hang.
    assert ray_tpu.get(p.call.remote(), timeout=60) == 1


def test_get_if_exists(cluster):
    @ray_tpu.remote
    class Singleton:
        def __init__(self):
            import os
            self.pid = os.getpid()

        def whoami(self):
            return self.pid

    a = Singleton.options(name="sing", get_if_exists=True).remote()
    b = Singleton.options(name="sing", get_if_exists=True).remote()
    assert ray_tpu.get(a.whoami.remote()) == ray_tpu.get(b.whoami.remote())


def test_kill_no_restart_false_restarts(cluster):
    import time

    @ray_tpu.remote(max_restarts=1, max_task_retries=1)
    class Cat:
        def ping(self):
            return "alive"

    c = Cat.remote()
    assert ray_tpu.get(c.ping.remote()) == "alive"
    ray_tpu.kill(c, no_restart=False)
    time.sleep(1.0)
    assert ray_tpu.get(c.ping.remote(), timeout=60) == "alive"


def test_cluster_resources(cluster):
    res = ray_tpu.cluster_resources()
    assert res.get("CPU") == 4.0


def test_threaded_actor_concurrency(cluster):
    """max_concurrency>1 runs actor tasks on a pool: N calls that each block
    on a barrier can only finish if they are truly in flight together
    (reference: concurrency_group_manager.h thread-pool execution)."""
    import threading

    @ray_tpu.remote(max_concurrency=4)
    class Barrier:
        def __init__(self, n):
            self._barrier = threading.Barrier(n, timeout=30)

        def rendezvous(self):
            idx = self._barrier.wait()
            return idx

    b = Barrier.remote(4)
    refs = [b.rendezvous.remote() for _ in range(4)]
    out = sorted(ray_tpu.get(refs, timeout=60))
    assert out == [0, 1, 2, 3]


def test_async_actor(cluster):
    """Coroutine methods execute on the actor's event loop with overlapping
    awaits (reference: async actors, fiber.h / actor event loop)."""
    import time

    @ray_tpu.remote
    class AsyncWorker:
        def __init__(self):
            self.active = 0
            self.peak = 0

        async def slow_echo(self, x):
            import asyncio
            self.active += 1
            self.peak = max(self.peak, self.active)
            await asyncio.sleep(0.3)
            self.active -= 1
            return x * 2

        async def peak_concurrency(self):
            return self.peak

    w = AsyncWorker.remote()
    # Warm-up: wait out worker spawn + actor creation before timing.
    ray_tpu.get(w.peak_concurrency.remote(), timeout=60)
    t0 = time.monotonic()
    refs = [w.slow_echo.remote(i) for i in range(8)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0, 2, 4, 6, 8, 10, 12, 14]
    elapsed = time.monotonic() - t0
    # 8 x 0.3s sleeps overlapped on one loop: far below the serial 2.4s.
    assert elapsed < 2.0
    assert ray_tpu.get(w.peak_concurrency.remote()) > 1


def test_cancel_queued_task(cluster):
    """A task still queued client-side is dropped without running
    (reference: ray.cancel worker.py:2793)."""
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote(num_cpus=4)
    def hog():
        time.sleep(5)
        return "hog"

    @ray_tpu.remote(num_cpus=4)
    def queued():
        return "ran"

    blocker = hog.remote()          # consumes every CPU slot
    time.sleep(0.3)
    victim = queued.remote()        # cannot schedule while hog runs
    ray_tpu.cancel(victim)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(victim, timeout=30)
    assert ray_tpu.get(blocker, timeout=30) == "hog"


def test_cancel_running_task(cluster):
    """force=False interrupts the running task thread."""
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote
    def spin():
        t0 = time.time()
        while time.time() - t0 < 30:
            time.sleep(0.01)
        return "finished"

    ref = spin.remote()
    time.sleep(1.0)  # let it start executing
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_cancel_force_kills_worker(cluster):
    from ray_tpu.exceptions import TaskCancelledError, WorkerCrashedError

    @ray_tpu.remote(max_retries=0)
    def spin():
        time.sleep(30)
        return "finished"

    ref = spin.remote()
    time.sleep(1.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises((TaskCancelledError, WorkerCrashedError)):
        ray_tpu.get(ref, timeout=30)


def test_checkpoint_directory_roundtrip(tmp_path):
    """Directory checkpoints with arbitrary files survive the dict form
    (ADVICE r1: to_dict used to drop everything but checkpoint.pkl)."""
    import pickle

    from ray_tpu.air import Checkpoint

    src = tmp_path / "ckpt"
    (src / "nested").mkdir(parents=True)
    (src / "weights.bin").write_bytes(b"\x00\x01\x02" * 100)
    (src / "nested" / "meta.txt").write_text("hello")

    ckpt = Checkpoint.from_directory(str(src))
    # Cross a (simulated) process boundary: pickle -> dict form.
    ckpt2 = pickle.loads(pickle.dumps(ckpt))
    out = ckpt2.to_directory(str(tmp_path / "restored"))
    assert (tmp_path / "restored" / "weights.bin").read_bytes() == \
        b"\x00\x01\x02" * 100
    assert (tmp_path / "restored" / "nested" / "meta.txt").read_text() == \
        "hello"

    # Dict-form checkpoints still round-trip through directories.
    c3 = Checkpoint.from_dict({"step": 7})
    d = c3.to_directory(str(tmp_path / "dictform"))
    assert Checkpoint.from_directory(d).to_dict()["step"] == 7


def test_state_api_and_cli(cluster):
    """State API lists live entities; CLI renders them (reference:
    experimental/state/api.py + scripts.py status/memory)."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu import state
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    class StateProbe:
        def ping(self):
            return 1

    probe = StateProbe.options(name="state-probe").remote()
    ray_tpu.get(probe.ping.remote())

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    actors = state.list_actors()
    assert any(a["class_name"] == "StateProbe" and a["state"] == "ALIVE"
               for a in actors)
    workers = state.list_workers()
    assert any(w["state"] == "actor" or w["actor_id"] for w in workers) \
        or len(workers) >= 1
    summary = state.summarize_cluster()
    assert summary["nodes_alive"] == 1
    assert summary["actors"].get("ALIVE", 0) >= 1

    address = cluster["gcs_address"]
    for argv in (["status", "--address", address],
                 ["list", "nodes", "--address", address],
                 ["list", "actors", "--address", address],
                 ["memory", "--address", address]):
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = cli.main(argv)
        assert rc == 0, argv
        assert buf.getvalue().strip(), argv
    ray_tpu.kill(probe)


def test_object_spilling_roundtrip(cluster):
    """Put 2x the store capacity, read everything back: pressure spills
    sealed objects to disk (hostd spill manager) and gets restore them
    (reference: external_storage.py:246 + local_object_manager.h:41)."""
    import numpy as np

    rng = np.random.default_rng(7)
    blobs = [rng.integers(0, 255, size=8 << 20, dtype=np.uint8)
             for _ in range(4)]
    refs = []
    for i in range(16):  # 16 x 8MB = 128MB through a 64MB store
        refs.append(ray_tpu.put(blobs[i % 4]))
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=60)
        np.testing.assert_array_equal(out, blobs[i % 4])
    # The store must have actually spilled (2x capacity cannot fit).
    from ray_tpu import state
    stats = [r for r in state.list_objects() if "capacity" in r]
    assert any(s.get("spilled_objects", 0) > 0 or
               s.get("spilled_bytes", 0) > 0 for s in stats)
    del refs


def test_runtime_env_env_vars_and_working_dir(cluster, tmp_path):
    """runtime_env: env_vars reach the worker process; working_dir ships
    through the GCS KV and becomes the task's cwd + sys.path (reference:
    _private/runtime_env/ working_dir.py + worker pool env-hash caching)."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "mymod.py").write_text("MAGIC = 'from-working-dir'\n")
    (proj / "data.txt").write_text("42")

    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "on"},
                                 "working_dir": str(proj)})
    def probe():
        import os
        import mymod
        with open("data.txt") as f:
            data = f.read()
        return os.environ.get("MY_FLAG"), mymod.MAGIC, data

    flag, magic, data = ray_tpu.get(probe.remote(), timeout=60)
    assert flag == "on"
    assert magic == "from-working-dir"
    assert data == "42"

    # Workers without the env must not see it (pool keyed by env hash).
    @ray_tpu.remote
    def plain():
        import os
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(plain.remote(), timeout=60) is None

    # pip envs are explicitly gated in this zero-egress deployment.
    with pytest.raises((NotImplementedError, Exception)):
        @ray_tpu.remote(runtime_env={"pip": ["requests"]})
        def nope():
            return 1
        ray_tpu.get(nope.remote(), timeout=30)


def test_runtime_env_on_actor(cluster, tmp_path):
    mod = tmp_path / "actormod"
    mod.mkdir()
    (mod / "helper.py").write_text("def gift():\n    return 'actor-env'\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)],
                                 "env_vars": {"WHO": "actor"}})
    class Envy:
        def peek(self):
            import os
            from helper import gift
            return os.environ["WHO"], gift()

    a = Envy.remote()
    assert ray_tpu.get(a.peek.remote(), timeout=60) == ("actor", "actor-env")
    ray_tpu.kill(a)


def test_system_config_flags(cluster):
    """Config registry: env override + _system_config validation
    (reference: ray_config_def.h RAY_CONFIG flags)."""
    import os

    from ray_tpu._private.config import GLOBAL_CONFIG, RayTpuConfig

    assert GLOBAL_CONFIG.task_max_retries == 3
    os.environ["RAY_TPU_TASK_MAX_RETRIES"] = "7"
    GLOBAL_CONFIG.invalidate_cache()
    try:
        assert GLOBAL_CONFIG.task_max_retries == 7
    finally:
        del os.environ["RAY_TPU_TASK_MAX_RETRIES"]
        GLOBAL_CONFIG.invalidate_cache()

    cfg = RayTpuConfig()
    cfg.apply_system_config({"lease_idle_ttl_s": 2.5})
    assert cfg.lease_idle_ttl_s == 2.5
    with pytest.raises(ValueError):
        cfg.apply_system_config({"not_a_flag": 1})
    dump = GLOBAL_CONFIG.dump()
    assert "spill_enabled" in dump and "heartbeat_interval_s" in dump


def test_metrics_api_and_export(cluster):
    """User metric API + cluster scrape + Prometheus text (reference:
    ray/util/metrics.py + stats/metric_defs.h + metrics agent export)."""
    from ray_tpu import state
    from ray_tpu.util import metrics as mt

    c = mt.Counter("test_requests", "requests served", ("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = mt.Gauge("test_temperature", "temp")
    g.set(3.5)
    h = mt.Histogram("test_latency", "latency", ("route",))
    h.observe(0.1, tags={"route": "/a"})
    h.observe(0.3, tags={"route": "/a"})

    snap = mt.collect()
    assert snap["test_requests"]["series"][0]["value"] == 3.0
    text = mt.prometheus_text()
    assert 'ray_tpu_test_requests{route="/a"} 3.0' in text
    assert "ray_tpu_test_latency_count" in text

    # Cluster-side: daemon metrics flow through the scrape RPCs.
    @ray_tpu.remote
    def touch():
        return 1

    ray_tpu.get(touch.remote())
    cm = state.cluster_metrics()
    node_metrics = list(cm["nodes"].values())[0]
    assert node_metrics["leases_granted"]["series"][0]["value"] >= 1
    assert node_metrics["workers_spawned"]["series"][0]["value"] >= 1
    prom = state.prometheus_metrics()
    assert "ray_tpu_leases_granted" in prom
    assert 'component="gcs"' in prom


def test_task_events_and_timeline(cluster, tmp_path):
    """Task execution events stream to the GCS; state.list_tasks and the
    Chrome-trace timeline render them (reference: TaskEventBuffer +
    `ray timeline`)."""
    import json
    import io
    from contextlib import redirect_stdout

    from ray_tpu import state
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    def traced_task(x):
        return x

    ray_tpu.get([traced_task.remote(i) for i in range(5)])
    deadline = time.time() + 15
    tasks = []
    while time.time() < deadline:
        tasks = [t for t in state.list_tasks()
                 if t["name"].endswith("traced_task")]
        if len(tasks) >= 5:
            break
        time.sleep(0.5)
    assert len(tasks) >= 5
    assert all(t["end"] >= t["start"] for t in tasks)

    out = tmp_path / "trace.json"
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["timeline", "--address", cluster["gcs_address"],
                       "--out", str(out)])
    assert rc == 0
    events = json.loads(out.read_text())
    assert any(e["ph"] == "X" and e["name"].endswith("traced_task")
               for e in events)


def test_memory_monitor_policy():
    """OOM victim policy: newest leased task worker first, actors only as
    a last resort (reference: raylet worker_killing_policy retriable-LIFO);
    /proc/meminfo probe returns a sane fraction."""
    from ray_tpu._private.hostd import NodeDaemon

    frac = NodeDaemon._read_memory_fraction()
    assert 0.0 < frac < 1.0

    class FakeProc:
        def poll(self):
            return None

    class H:
        def __init__(self, state, t):
            self.state = state
            self.leased_at = t
            self.proc = FakeProc()

    daemon = NodeDaemon.__new__(NodeDaemon)  # policy only; no daemon state
    daemon.workers = {1: H("idle", 0), 2: H("leased", 10.0),
                      3: H("leased", 20.0), 4: H("actor", 30.0)}
    assert NodeDaemon._pick_oom_victim(daemon).leased_at == 20.0
    daemon.workers = {1: H("idle", 0), 4: H("actor", 30.0)}
    assert NodeDaemon._pick_oom_victim(daemon).state == "actor"
    daemon.workers = {1: H("idle", 0)}
    assert NodeDaemon._pick_oom_victim(daemon) is None


def test_worker_logs_stream_to_gcs(cluster):
    """Worker prints reach the GCS log channel tagged with pid/stream
    (reference: log_monitor -> GCS pubsub -> driver echo)."""
    from ray_tpu import api

    @ray_tpu.remote
    def chatty():
        print("hello-from-worker-stdout")
        import sys
        print("hello-from-worker-stderr", file=sys.stderr)
        return 1

    ray_tpu.get(chatty.remote())
    w = api._worker
    deadline = time.time() + 15
    seen = set()
    while time.time() < deadline and len(seen) < 2:
        reply = w.io.run(w.gcs.call("Gcs", "get_log_lines",
                                    {"after_seq": 0}), timeout=10)
        for _seq, rec in reply["lines"]:
            if "hello-from-worker" in rec["line"]:
                seen.add(rec["stream"])
        time.sleep(0.3)
    assert seen == {"stdout", "stderr"}


def test_pubsub_channels(cluster):
    """Named pub/sub channels with long-poll subscribers (reference:
    src/ray/pubsub + gcs_pubsub.py)."""
    import threading

    from ray_tpu.util import Publisher, Subscriber

    pub = Publisher("events")
    sub = Subscriber("events")
    assert sub.poll(timeout_s=0.2) == []    # empty channel times out

    pub.publish({"kind": "a"}, {"kind": "b"})
    msgs = sub.poll(timeout_s=5)
    assert [m["kind"] for m in msgs] == ["a", "b"]
    assert sub.poll(timeout_s=0.2) == []    # cursor advanced

    # Long-poll actually parks: publish from another thread mid-poll.
    got = []

    def publish_later():
        time.sleep(0.5)
        Publisher("events").publish({"kind": "late"})

    t = threading.Thread(target=publish_later)
    t.start()
    t0 = time.monotonic()
    msgs = sub.poll(timeout_s=10)
    elapsed = time.monotonic() - t0
    t.join()
    assert [m["kind"] for m in msgs] == ["late"]
    assert 0.3 < elapsed < 5.0  # woke on publish, not timeout

    # A second subscriber from seq 0 replays the ring.
    sub2 = Subscriber("events")
    assert len(sub2.poll(timeout_s=2)) == 3


def test_stack_traces(cluster):
    """`ray_tpu stack` equivalent: live thread dumps show a worker inside
    the running task (reference: `ray stack`, scripts.py:1798)."""
    import time as _time

    from ray_tpu import state

    @ray_tpu.remote
    def marker_fn_sleeps():
        _time.sleep(45)
        return 1

    ref = marker_fn_sleeps.remote()
    dumped = ""
    deadline = _time.time() + 60
    while _time.time() < deadline:   # worker spawn can be slow on 1 cpu
        per_node = state.stack_traces()
        dumped = "\n".join(
            th["stack"]
            for reply in per_node.values()
            for proc in reply.get("processes", [])
            for th in proc["threads"])
        if "marker_fn_sleeps" in dumped:
            break
        _time.sleep(1.0)
    assert "marker_fn_sleeps" in dumped
    # the daemon reports itself too
    kinds = {proc["kind"] for reply in per_node.values()
             for proc in reply.get("processes", [])}
    assert "hostd" in kinds
    ray_tpu.cancel(ref, force=True)
    from ray_tpu.exceptions import (
        TaskCancelledError, WorkerCrashedError)
    with pytest.raises((TaskCancelledError, WorkerCrashedError)):
        ray_tpu.get(ref, timeout=60)


def test_trace_context_propagates_across_tasks(cluster):
    """Span propagation (reference: tracing_helper.py:87 — context is
    injected at submit, extracted at execute): a driver trace scope
    covers a task AND the task's own nested submission, and the timeline
    events carry the shared trace_id with a parent/child span chain."""
    from ray_tpu import state
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def inner():
        return "leaf"

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(inner.remote())

    with tracing.trace("req") as trace_id:
        assert ray_tpu.get(outer.remote()) == "leaf"
    # Outside the scope nothing attaches.
    assert tracing.current_context() is None

    deadline = time.time() + 15
    traced = []
    while time.time() < deadline:
        traced = [t for t in state.list_tasks()
                  if t.get("trace_id") == trace_id]
        if len(traced) >= 2:
            break
        time.sleep(0.5)
    names = {t["name"].split(".")[-1] for t in traced}
    assert {"outer", "inner"} <= names
    by_name = {t["name"].split(".")[-1]: t for t in traced}
    # inner's parent span is outer's span.
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]


def _make_wheel(wheelhouse, name="tinypkg", version="1.0", value=41):
    """Hand-build a minimal pure-python wheel (no network, no build
    backend): a wheel is just a zip with package code + dist-info."""
    import os
    import zipfile
    os.makedirs(wheelhouse, exist_ok=True)
    whl = os.path.join(wheelhouse, f"{name}-{version}-py3-none-any.whl")
    di = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as z:
        z.writestr(f"{name}/__init__.py", f"VALUE = {value}\n")
        z.writestr(f"{di}/METADATA",
                   f"Metadata-Version: 2.1\nName: {name}\n"
                   f"Version: {version}\n")
        z.writestr(f"{di}/WHEEL",
                   "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: "
                   "true\nTag: py3-none-any\n")
        z.writestr(f"{di}/RECORD", "")
    return whl


def test_runtime_env_pip_local_wheelhouse(cluster, tmp_path):
    """runtime_env pip installs from a local wheelhouse — offline
    `--no-index --find-links` (reference: _private/runtime_env/pip.py's
    per-requirements-hash cached env; VERDICT r2 missing 8: zero-egress
    satisfied by a wheelhouse instead of the network)."""
    wheelhouse = str(tmp_path / "wheels")
    _make_wheel(wheelhouse, value=41)

    @ray_tpu.remote(runtime_env={"pip": {"packages": ["tinypkg"],
                                         "wheelhouse": wheelhouse}})
    def uses_pkg():
        import tinypkg
        return tinypkg.VALUE + 1

    assert ray_tpu.get(uses_pkg.remote(), timeout=120) == 42

    # The package must NOT leak into default-env workers.
    @ray_tpu.remote
    def plain():
        import importlib.util
        return importlib.util.find_spec("tinypkg") is None

    assert ray_tpu.get(plain.remote(), timeout=60)


def test_runtime_env_pip_requires_wheelhouse(cluster, monkeypatch):
    # The env-var fallback is the documented deployment mechanism; it
    # must not leak into this negative test.
    monkeypatch.delenv("RAY_TPU_WHEELHOUSE", raising=False)
    with pytest.raises(ValueError):
        @ray_tpu.remote(runtime_env={"pip": ["whatever"]})
        def f():
            return 1
        f.remote()


def test_joblib_backend_and_check_serialize(cluster):
    """joblib.parallel_backend('ray') runs joblib workloads on cluster
    tasks (reference: util/joblib register_ray), and the
    serializability inspector localizes unpicklable members (reference:
    util/check_serialize)."""
    import joblib

    from ray_tpu.util.joblib_backend import (
        check_serializability,
        register_ray,
    )

    register_ray()
    with joblib.parallel_backend("ray", n_jobs=2):
        out = joblib.Parallel()(
            joblib.delayed(lambda x: x * x)(i) for i in range(12))
    assert out == [i * i for i in range(12)]

    assert check_serializability({"fine": [1, 2, 3]}) == []
    import threading
    problems = check_serializability({"bad": threading.Lock()})
    assert problems and any("bad" in p for p in problems)


def test_pool_async_callbacks(cluster):
    """stdlib parity: apply_async/map_async/starmap_async fire
    callback/error_callback (one shared drainer thread, not one thread
    per submission)."""
    import threading

    from ray_tpu.util.multiprocessing import Pool

    got, errs = [], []
    done = threading.Event()
    with Pool(processes=2) as pool:
        pool.apply_async(lambda x: x + 1, (41,),
                         callback=lambda v: (got.append(v), done.set()))
        assert done.wait(60)
        assert got == [42]

        done2 = threading.Event()
        pool.map_async(lambda x: x * 2, [1, 2, 3],
                       callback=lambda v: (got.append(v), done2.set()))
        assert done2.wait(60)
        assert got[-1] == [2, 4, 6]

        done3 = threading.Event()

        def boom(_):
            raise RuntimeError("pool-cb-error")

        pool.apply_async(boom, (0,),
                         error_callback=lambda e: (errs.append(str(e)),
                                                   done3.set()))
        assert done3.wait(60)
        assert errs and "pool-cb-error" in errs[0]

        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
