"""Device-feed input pipeline tests: incremental batch assembly, the
overlapped producer/device iterator (exactness + buffer bounds), and
work-stealing dataset splits (exactly-once coverage under stragglers and
worker death, deterministic mode byte-identity)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data import block as blk
from ray_tpu.data import ingest


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


def _ids(batches):
    out = []
    for b in batches:
        out.extend(int(x) for x in np.asarray(b["id"]))
    return out


# ---------------------------------------------------------------------------
# Incremental assembly (the O(n^2) satellite)
# ---------------------------------------------------------------------------


def test_batch_assembler_row_cursor_exact(cluster):
    # Blocks deliberately misaligned with the batch size: every batch
    # spans a block boundary somewhere.
    blocks = [blk.rows_to_block([{"id": i} for i in range(lo, lo + n)])
              for lo, n in [(0, 7), (7, 13), (20, 1), (21, 29), (50, 50)]]
    batches = list(ingest.batches_from_block_iter(iter(blocks), 16))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [16] * 6 + [4]  # 100 rows -> 6 full + tail
    assert _ids(batches) == list(range(100))
    # drop_last drops exactly the partial tail.
    dropped = list(ingest.batches_from_block_iter(iter(blocks), 16,
                                                  drop_last=True))
    assert _ids(dropped) == list(range(96))


def test_assembler_buffers_only_the_tail(cluster):
    # The row cursor must RELEASE consumed blocks: after draining full
    # batches, at most one partial block's rows stay buffered.
    asm = ingest.BatchAssembler(10)
    for lo in range(0, 90, 30):
        asm.add_block(blk.rows_to_block([{"id": i}
                                         for i in range(lo, lo + 30)]))
        while asm.next_batch() is not None:
            pass
        assert asm.buffered_rows < 10
        assert len(asm._blocks) <= 1


def test_iter_batches_matches_take_all(cluster):
    ds = rd.range(500, parallelism=7).map(
        lambda r: {"id": r["id"], "x": float(r["id"]) * 0.5})
    got = _ids(ds.iter_batches(batch_size=64))
    assert got == list(range(500))


# ---------------------------------------------------------------------------
# Overlapped producer + device feed
# ---------------------------------------------------------------------------


def test_device_iter_exactness_gate(cluster):
    """The overlapped device feed must be numerically identical to the
    sync path, batch for batch."""
    ds = rd.range(600, parallelism=8).map(
        lambda r: {"id": r["id"], "x": float(r["id"]) ** 2})
    it = ds.streaming_split(1)[0]
    sync = [{k: v.copy() for k, v in b.items()}
            for b in it.iter_batches(batch_size=96)]
    dev = list(it.iter_device_batches(batch_size=96))
    assert len(sync) == len(dev)
    for s, d in zip(sync, dev):
        assert set(s) == set(d)
        for k in s:
            np.testing.assert_array_equal(s[k], np.asarray(d[k]))


def test_device_iter_respects_buffer_bounds(cluster):
    """Neither the handoff queue nor the device stage may buffer more
    than its configured bound, even under a slow consumer."""
    ds = rd.range(800, parallelism=8)
    it = ds.streaming_split(1)[0]
    dev = it.iter_device_batches(batch_size=50, queue_depth=3,
                                 device_buffers=2)
    for _ in dev:
        time.sleep(0.01)  # consumer is the bottleneck: queues fill
    stats = dev.stats()
    assert stats["batches"] == 16
    assert stats["max_queue_depth"] <= 3
    assert stats["max_device_inflight"] <= 2
    # Slow consumer => the producer spent time blocked on a full queue.
    assert stats["producer_wait_s"] > 0


def test_producer_error_propagates(cluster):
    def boom(_):
        raise RuntimeError("ingest boom")

    producer = ingest.BatchProducer(map(boom, range(3)), 10)
    with pytest.raises(RuntimeError, match="ingest boom"):
        list(producer)


def test_session_iter_device_batches_convenience(cluster):
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

    def loop():
        from ray_tpu.train import session
        total = 0
        for b in session.iter_device_batches("train", batch_size=40):
            total += int(np.asarray(b["id"]).shape[0])
        session.report({"rows": total})

    ds = rd.range(400, parallelism=8)
    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rows"] == 200  # equal split of 400 over 2


# ---------------------------------------------------------------------------
# Work-stealing splits
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _Consumer:
    """Drains a shard iterator, optionally sleeping per batch (straggler
    injection); batch_size aligns to the block size so one batch == one
    block lease."""

    def __init__(self, it, delay: float = 0.0):
        self._it = it
        self._delay = delay

    def run(self, batch_size: int):
        ids = []
        for b in self._it.iter_batches(batch_size=batch_size):
            ids.extend(int(x) for x in b["id"])
            if self._delay:
                time.sleep(self._delay)
        return ids


@ray_tpu.remote
class _Sink:
    """Cross-rank row collector for the trainer wiring test."""

    def __init__(self):
        self._ids = []

    def add(self, ids):
        self._ids.extend(ids)

    def all(self):
        return self._ids


@ray_tpu.remote
class _Leaser:
    """Takes exactly one lease and never completes it (death injection)."""

    def __init__(self, coord, worker: int):
        self._coord = coord
        self._worker = worker

    def lease_one(self):
        ray_tpu.get(self._coord.register.remote(self._worker, []))
        return ray_tpu.get(self._coord.next.remote(self._worker, None))


def test_stealing_covers_every_block_once_with_slow_worker(cluster):
    ds = rd.range(1000, parallelism=8)  # 8 blocks x 125 rows
    its = ds.streaming_split(2, equal=True, steal=True)
    slow = _Consumer.remote(its[0], 0.4)
    fast = _Consumer.remote(its[1], 0.0)
    a, b = ray_tpu.get([slow.run.remote(125), fast.run.remote(125)],
                       timeout=120)
    combined = sorted(a + b)
    assert combined == list(range(1000))  # exactly once, no loss, no dup
    # The fast worker must have taken over straggler blocks.
    stats = ray_tpu.get(its[0].coordinator().stats.remote())
    assert stats["stolen"] >= 1
    assert len(b) > len(a)


@pytest.mark.chaos
def test_lease_requeue_on_worker_death(cluster):
    ds = rd.range(1000, parallelism=8)
    its = ds.streaming_split(2, equal=True, steal=True)
    coord = its[0].coordinator()
    # Worker 0 leases one block and dies without completing it.
    victim = _Leaser.remote(coord, 0)
    lease = ray_tpu.get(victim.lease_one.remote())
    assert lease is not None
    ray_tpu.kill(victim)
    assert ray_tpu.get(coord.mark_dead.remote(0)) == 1
    # The survivor covers the ENTIRE pool, including the re-queued lease.
    survivor = _Consumer.remote(its[1], 0.0)
    ids = ray_tpu.get(survivor.run.remote(125), timeout=120)
    assert sorted(ids) == list(range(1000))
    stats = ray_tpu.get(coord.stats.remote())
    assert stats["requeued"] == 1
    assert stats["remaining"] == 0


@pytest.mark.chaos
def test_lease_timeout_reaps_silent_worker(cluster):
    """Without an explicit mark_dead, a crashed worker's lease re-queues
    once it has been silent past lease_timeout_s and the pool is dry."""
    ds = rd.range(400, parallelism=4)
    its = ds.streaming_split(2, equal=True, steal=True,
                             lease_timeout_s=0.5)
    coord = its[0].coordinator()
    victim = _Leaser.remote(coord, 0)
    assert ray_tpu.get(victim.lease_one.remote()) is not None
    ray_tpu.kill(victim)  # silent death: no mark_dead
    survivor = _Consumer.remote(its[1], 0.0)
    ids = ray_tpu.get(survivor.run.remote(100), timeout=120)
    assert sorted(ids) == list(range(400))
    assert ray_tpu.get(coord.stats.remote())["requeued"] == 1


def test_deterministic_mode_byte_identical(cluster):
    ds = rd.range(500, parallelism=8).map(
        lambda r: {"id": r["id"], "x": float(r["id"]) * 3})
    runs = []
    for _ in range(2):
        its = ds.streaming_split(2, equal=True, steal=True,
                                 deterministic=True)
        runs.append([[{k: v.copy() for k, v in b.items()}
                      for b in it.iter_batches(batch_size=64)]
                     for it in its])
    static = [list(it.iter_batches(batch_size=64))
              for it in ds.streaming_split(2, equal=True)]
    for other in (runs[1], static):
        for shard_a, shard_b in zip(runs[0], other):
            assert len(shard_a) == len(shard_b)
            for ba, bb in zip(shard_a, shard_b):
                for k in ba:
                    np.testing.assert_array_equal(ba[k], bb[k])


def test_trainer_steal_flag_wires_coordinated_shards(cluster):
    """ingest_work_stealing=True routes trainer shards through the
    coordinator; every row is still consumed exactly once across the
    gang."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

    def loop(cfg):
        from ray_tpu.train import session
        ids = []
        for b in session.get_dataset_shard("train").iter_batches(
                batch_size=50):
            ids.extend(int(x) for x in b["id"])
        ray_tpu.get(cfg["sink"].add.remote(ids))
        session.report({"rows": len(ids)})

    sink = _Sink.remote()
    GLOBAL_CONFIG.apply_system_config({"ingest_work_stealing": True})
    try:
        ds = rd.range(400, parallelism=8)
        trainer = DataParallelTrainer(
            loop, train_loop_config={"sink": sink},
            scaling_config=ScalingConfig(num_workers=2),
            datasets={"train": ds})
        result = trainer.fit()
    finally:
        GLOBAL_CONFIG.apply_system_config({"ingest_work_stealing": False})
    assert result.error is None
    assert sorted(ray_tpu.get(sink.all.remote())) == list(range(400))


# ---------------------------------------------------------------------------
# Executor satellites
# ---------------------------------------------------------------------------


def test_local_nbytes_reads_store_without_probe_task(cluster):
    from ray_tpu.data.executor import _local_nbytes
    table = blk.rows_to_block([{"id": i, "x": float(i)}
                               for i in range(5000)])
    ref = ray_tpu.put(table)
    n = _local_nbytes(ref)
    assert n is not None and n > 0


def test_byte_window_sizes_from_local_store(cluster):
    """_ByteWindow must reach a byte-derived limit from the local store
    alone (no probe task needed for locally sealed blocks)."""
    from ray_tpu.data.executor import _ByteWindow
    table = blk.rows_to_block([{"id": i} for i in range(50000)])
    ref = ray_tpu.put(table)
    bw = _ByteWindow(window=64, window_bytes=1 << 20)
    bw.observe(ref)
    limit = bw.limit()
    assert bw._est is not None and bw._probe is None
    assert 1 <= limit <= 64
