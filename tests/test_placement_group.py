"""Placement group tests against a real multi-node (multi-hostd) cluster.

Reference coverage model: python/ray/tests/test_placement_group*.py over
cluster_utils.Cluster.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    get_current_placement_group, placement_group, placement_group_table,
    remove_placement_group)


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2)
    c.wait_for_nodes()
    c.connect()
    yield c
    c.shutdown()


def test_strict_spread_lands_on_distinct_nodes(cluster):
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(30)

    @ray_tpu.remote(num_cpus=1)
    def where():
        import os
        return os.environ.get("RAY_TPU_NODE_ID")

    nodes = ray_tpu.get([
        where.options(placement_group=pg,
                      placement_group_bundle_index=i).remote()
        for i in range(3)])
    assert len(set(nodes)) == 3
    remove_placement_group(pg)


def test_strict_pack_lands_on_one_node(cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.wait(30)

    @ray_tpu.remote(num_cpus=1)
    def where():
        import os
        return os.environ.get("RAY_TPU_NODE_ID")

    nodes = ray_tpu.get([
        where.options(placement_group=pg,
                      placement_group_bundle_index=i).remote()
        for i in range(2)])
    assert len(set(nodes)) == 1
    remove_placement_group(pg)


def test_infeasible_pg_stays_pending(cluster):
    pg = placement_group([{"CPU": 64}], strategy="PACK")
    assert not pg.wait(1.0)
    remove_placement_group(pg)


def test_bundle_capacity_enforced(cluster):
    # One 1-CPU bundle: two concurrent 1-CPU tasks must serialize on it.
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(num_cpus=1)
    def stamp():
        import time as t
        start = t.monotonic()
        t.sleep(0.4)
        return (start, t.monotonic())

    a, b = ray_tpu.get([
        stamp.options(placement_group=pg).remote() for _ in range(2)],
        timeout=60)
    # Intervals must not overlap (single-slot bundle).
    overlap = min(a[1], b[1]) - max(a[0], b[0])
    assert overlap <= 0.05, f"tasks overlapped by {overlap:.3f}s"
    remove_placement_group(pg)


def test_actor_in_pg_and_remove_kills_actor(cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(num_cpus=1)
    class A:
        def pg_id(self):
            cur = get_current_placement_group()
            return cur.id.hex() if cur else None

        def ping(self):
            return "pong"

    a = A.options(placement_group=pg).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    assert ray_tpu.get(a.pg_id.remote()) == pg.id.hex()

    remove_placement_group(pg)
    deadline = time.monotonic() + 20
    died = False
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(a.ping.remote(), timeout=5)
        except Exception:
            died = True
            break
        time.sleep(0.2)
    assert died, "actor survived placement group removal"


def test_placement_group_table(cluster):
    pg = placement_group([{"CPU": 1}], strategy="SPREAD", name="tbl")
    assert pg.wait(30)
    table = placement_group_table()
    entry = table[pg.id.hex()]
    assert entry["name"] == "tbl"
    assert entry["state"] == "CREATED"
    assert entry["bundles"][0] == {"CPU": 1}
    remove_placement_group(pg)


def test_pg_resources_returned_after_remove(cluster):
    total = ray_tpu.cluster_resources().get("CPU", 0)
    # Quiesce: wait for resources leaked back from earlier tests so the
    # baseline is stable (the GCS view refreshes with node heartbeats).
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) >= total - 1e-6:
            break
        time.sleep(0.2)
    before = ray_tpu.available_resources().get("CPU", 0)
    assert before >= total - 1e-6
    pg = placement_group([{"CPU": 1}] * 2, strategy="SPREAD")
    assert pg.wait(30)
    deadline = time.monotonic() + 10
    during = before
    while time.monotonic() < deadline:
        during = ray_tpu.available_resources().get("CPU", 0)
        if during <= before - 2 + 1e-6:
            break
        time.sleep(0.2)
    assert during <= before - 2 + 1e-6
    remove_placement_group(pg)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) >= before - 1e-6:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources().get("CPU", 0) >= before - 1e-6
