from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID


def test_sizes_and_hex():
    n = NodeID.from_random()
    assert len(n.binary()) == 20
    assert NodeID.from_hex(n.hex()) == n


def test_object_id_embeds_lineage():
    job = JobID.next()
    actor = ActorID.of(job)
    task = TaskID.of(actor)
    obj = ObjectID.for_return(task, 2)
    assert obj.task_id() == task
    assert obj.return_index() == 2
    assert not obj.is_put()
    assert task.actor_id() == actor
    assert actor.job_id() == job


def test_put_ids_distinct_from_returns():
    task = TaskID.of()
    a = ObjectID.for_return(task, 1)
    b = ObjectID.for_put(task, 1)
    assert a != b
    assert b.is_put() and b.return_index() == 1


def test_nil():
    assert ActorID.nil().is_nil()
    assert not ActorID.of(JobID.next()).is_nil()
