"""Train library tests: worker gangs, jax.distributed rendezvous across
actor processes (2 workers x 2 virtual CPU devices = 4-device fabric),
session streaming, checkpoints, elastic restart.

Reference coverage model: python/ray/train/tests/test_backend.py +
test_data_parallel_trainer.py, with the torch/NCCL fabric replaced by
multi-controller JAX on CPU.
"""

import os

import pytest

import ray_tpu
from ray_tpu.air import Checkpoint, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import (
    DataParallelTrainer, JaxTrainer, TpuConfig)

WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    # Workers inherit the test process env; these must not leak through.
    "PALLAS_AXON_POOL_IPS": "",
}


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_data_parallel_trainer_basic(cluster):
    def loop(config):
        from ray_tpu.train import session
        for step in range(config["steps"]):
            session.report({"step": step,
                            "rank": session.get_world_rank(),
                            "world": session.get_world_size()})

    trainer = DataParallelTrainer(
        loop, train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics["step"] == 2
    assert result.metrics["world"] == 2


def test_jax_trainer_distributed_fabric(cluster):
    """2 worker processes x 2 CPU devices -> one 4-device jax fabric with a
    cross-process psum (the ICI-collective path, simulated on CPU)."""

    def loop():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from ray_tpu.train import session

        assert jax.process_count() == 2
        assert len(jax.devices()) == 4
        mesh = Mesh(np.array(jax.devices()), ("data",))
        sharding = NamedSharding(mesh, P("data"))
        # Each process contributes its local shard of the global array.
        local = np.full((2, 4), 1.0 + jax.process_index(), np.float32)
        arr = jax.make_array_from_process_local_data(sharding, local, (4, 4))
        total = jax.jit(lambda x: jnp.sum(x))(arr)   # cross-process reduce
        session.report({"total": float(total),
                        "devices": len(jax.devices())})

    trainer = JaxTrainer(
        loop,
        jax_config=TpuConfig(env_per_worker=WORKER_ENV),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None
    # 8 elements of 1.0 (process 0) + 8 of 2.0 (process 1) = 24.
    assert result.metrics["total"] == 24.0
    assert result.metrics["devices"] == 4


def test_trainer_checkpointing(cluster, tmp_path):
    def loop(config):
        from ray_tpu.train import session
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 4):
            session.report({"step": step},
                           checkpoint=Checkpoint.from_dict({"step": step}))

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ckpt_run", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint.to_dict() == {"step": 3}
    saved = sorted(os.listdir(tmp_path / "ckpt_run"))
    assert len(saved) == 4

    # Resume from the checkpoint: only remaining steps run.
    trainer2 = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        resume_from_checkpoint=result.checkpoint)
    result2 = trainer2.fit()
    assert result2.error is None
    assert result2.metrics_history == []  # start=4: nothing left to do


def test_trainer_error_propagates(cluster):
    def loop():
        from ray_tpu.train import session
        session.report({"step": 0})
        raise RuntimeError("boom in train loop")

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is not None
    assert "boom" in str(result.error)
    assert len(result.metrics_history) == 1


def test_trainer_elastic_restart(cluster, tmp_path):
    marker = tmp_path / "crashed_once"

    def loop(config):
        import os as _os
        from ray_tpu.train import session
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 5):
            if step == 2 and session.get_world_rank() == 0 \
                    and not _os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                _os._exit(1)  # hard-kill this worker mid-training
            session.report({"step": step},
                           checkpoint=Checkpoint.from_dict({"step": step}))

    trainer = DataParallelTrainer(
        loop, train_loop_config={"marker": str(marker)},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 4
    assert marker.exists()


def test_jax_trainer_gpt_finetune_e2e(cluster):
    """BASELINE.md target: GPT LM fine-tune, DataParallelTrainer-equivalent,
    across a multi-worker jax fabric (nano config on the CPU mesh)."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax
        from ray_tpu.models import gpt
        from ray_tpu.parallel import MeshConfig, create_mesh, global_batch
        from ray_tpu.train import session

        cfg = gpt.CONFIGS["nano"]
        mesh = create_mesh(MeshConfig(data=-1))  # all 4 global devices
        init_state, train_step = gpt.make_train_step(
            cfg, optax.adam(1e-2), mesh)
        state = init_state(jax.random.key(0))
        step = jax.jit(train_step, donate_argnums=0)

        rng = np.random.default_rng(session.get_world_rank())
        local = rng.integers(0, cfg.vocab_size, (4, 32), dtype=np.int32)
        batch = global_batch(mesh, {"tokens": local})
        for i in range(config["steps"]):
            state, metrics = step(state, batch)
            session.report({"loss": float(metrics["loss"]), "step": i})

    trainer = JaxTrainer(
        loop, train_loop_config={"steps": 4},
        jax_config=TpuConfig(env_per_worker=WORKER_ENV),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]


def test_data_to_train_streaming_ingest(cluster):
    """Data -> Train: each worker iterates ITS OWN shard stream via
    session.get_dataset_shard (reference: DataParallelTrainer datasets= +
    streaming_split ingest)."""
    from ray_tpu import data as rdata
    from ray_tpu import train
    from ray_tpu.train import session

    ds = rdata.range(512).map(lambda r: {"id": r["id"], "x": float(r["id"])})

    def loop():
        shard = session.get_dataset_shard("train")
        ctx = session.get_context()
        rows = 0
        total = 0.0
        for batch in shard.iter_batches(batch_size=64):
            rows += len(batch["x"])
            total += float(batch["x"].sum())
        session.report({"rows": rows, "total": total,
                        "rank": ctx.world_rank})

    from ray_tpu.air import ScalingConfig
    trainer = train.JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None
    # Rank-0 metrics: each worker saw exactly half the rows.
    assert result.metrics["rows"] == 256


def test_torch_trainer_ddp_gloo(cluster):
    """TorchTrainer: 2 workers form a real torch.distributed gloo group
    and allreduce gradients (reference: train/torch/config.py:155 +
    torch_trainer.py — the collective is torch's own, not ours)."""
    from ray_tpu import train
    from ray_tpu.train import session

    def loop():
        import torch
        import torch.distributed as dist

        rank = dist.get_rank()
        world = dist.get_world_size()
        model = torch.nn.Linear(4, 1, bias=False)
        with torch.no_grad():
            model.weight.fill_(1.0)
        # Rank-dependent data -> rank-dependent grads; allreduce averages.
        x = torch.full((8, 4), float(rank + 1))
        loss = model(x).sum()
        loss.backward()
        dist.all_reduce(model.weight.grad, op=dist.ReduceOp.SUM)
        model.weight.grad /= world
        session.report({
            "rank": rank, "world": world,
            "grad0": float(model.weight.grad[0, 0]),
        })

    trainer = train.TorchTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}))
    result = trainer.fit()
    assert result.error is None
    # grads: rank0 data=1 -> grad 8; rank1 data=2 -> grad 16; mean = 12.
    assert result.metrics["grad0"] == pytest.approx(12.0)
    assert result.metrics["world"] == 2
