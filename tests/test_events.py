"""Flight-recorder + SLO-metrics observability suite (PR 10).

Covers the ISSUE checklist: ring overflow keeps the newest N, append is
re-entrant from signal handlers, crash dumps survive a scripted chaos
kill and `state.events()` stitches them with live peers by trace id,
bucket-quantile math agrees with numpy, and the `cli events` / `cli top`
commands render a live cluster.
"""

import bisect
import io
import json
import os
import signal
import time
from contextlib import redirect_stdout

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.util import events
from ray_tpu.util import metrics as mt
from ray_tpu.util import tracing
from ray_tpu.util.events import FlightRecorder


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Each test starts with an empty process ring and re-reads config."""
    events.reset()
    yield
    events.reset()
    GLOBAL_CONFIG.invalidate_cache()


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_ring_overflow_keeps_newest():
    r = FlightRecorder(capacity=16)
    for i in range(40):
        r.append("engine", "step", {"i": i})
    snap = r.snapshot()
    assert len(snap) == 16
    # Overflow overwrote the oldest: exactly seqs 24..39 survive, in order.
    assert [e["seq"] for e in snap] == list(range(24, 40))
    assert [e["payload"]["i"] for e in snap] == list(range(24, 40))


def test_snapshot_filters_plane_kind_since():
    r = FlightRecorder(capacity=64)
    r.append("serve", "admit", {"a": 1})
    t_mid = time.time()
    time.sleep(0.01)
    r.append("engine", "submit", {"b": 2})
    r.append("engine", "finish", None)
    assert [e["kind"] for e in r.snapshot(plane="engine")] == \
        ["submit", "finish"]
    assert [e["kind"] for e in r.snapshot(kind="admit")] == ["admit"]
    assert all(e["ts"] >= t_mid for e in r.snapshot(since=t_mid))
    assert [e["kind"] for e in r.snapshot(since=t_mid)] == \
        ["submit", "finish"]


def test_tail_returns_last_n():
    r = FlightRecorder(capacity=128)
    for i in range(80):
        r.append("proc", "tick", {"i": i})
    tail = r.tail(50)
    assert len(tail) == 50
    assert tail[-1]["payload"]["i"] == 79
    assert tail[0]["payload"]["i"] == 30


def test_record_carries_active_trace_context():
    with tracing.trace("obs-test") as tid:
        events.record("engine", "submit", rid=1)
    events.record("engine", "submit", rid=2)
    snap = events.snapshot(kind="submit")
    assert snap[0]["trace_id"] == tid and snap[0]["span_id"]
    assert snap[1]["trace_id"] is None


def test_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("RAY_TPU_EVENTS", "0")
    GLOBAL_CONFIG.invalidate_cache()
    events.reset()
    events.record("engine", "submit", rid=1)
    assert not events.enabled()
    assert events.snapshot() == []


def test_append_reentrant_from_signal_handler():
    """A SIGALRM handler that itself appends must not corrupt the ring:
    the seq counter is a single C-level next() and the slot store is one
    list assignment, so interleaved appends land in distinct slots."""
    fired = [0]

    def on_alarm(signum, frame):
        fired[0] += 1
        events.record("proc", "sig", n=fired[0])

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, 0.0005, 0.0005)
    try:
        for i in range(30000):
            events.record("engine", "main", i=i)
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, old)
    assert fired[0] >= 1, "timer never fired; test environment broken"
    snap = events.snapshot()
    # Every surviving slot is a well-formed event and seqs are unique
    # and strictly increasing after the snapshot sort.
    seqs = [e["seq"] for e in snap]
    assert len(seqs) == len(set(seqs))
    assert seqs == sorted(seqs)
    assert all(e["plane"] in ("engine", "proc") for e in snap)
    sig_events = [e for e in snap if e["kind"] == "sig"]
    assert len(sig_events) >= 1


# ---------------------------------------------------------------------------
# Crash dumps (the black box)
# ---------------------------------------------------------------------------


def test_crash_dump_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_FLIGHTREC_DIR", str(tmp_path))
    with tracing.trace("blackbox") as tid:
        events.record("serve", "admit", deployment="d")
        events.record("engine", "submit", rid=7)
    path = events.dump_crash("unit_test_kill")
    assert path and os.path.exists(path)
    assert os.path.basename(path) == \
        f"flightrec-{os.getpid()}-{os.environ.get('RAY_TPU_CHAOS_PROC_SALT') or '0'}.jsonl"
    # Header line + one line per event, all valid json.
    lines = open(path).read().splitlines()
    header = json.loads(lines[0])
    assert header["_flightrec"] == 1 and header["pid"] == os.getpid()
    assert header["reason"] == "unit_test_kill"
    out = events.read_dumps(str(tmp_path))
    assert out and all(e["source"] == "crash" for e in out)
    assert all(e["reason"] == "unit_test_kill" for e in out)
    assert all(e["pid"] == os.getpid() for e in out)
    kinds = {e["kind"] for e in out}
    # The dump itself is recorded, so forensics show the dump reason too.
    assert {"admit", "submit", "crash_dump"} <= kinds
    traced = [e for e in out if e["trace_id"] == tid]
    # The trace ctxmanager contributes its own root span edges (PR 11).
    assert {e["kind"] for e in traced} == {"admit", "submit", "trace"}


def test_read_dumps_skips_corrupt_files(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_FLIGHTREC_DIR", str(tmp_path))
    events.record("proc", "ok")
    assert events.dump_crash("good")
    # Debris that must be ignored: truncated dump, non-dump jsonl, junk.
    (tmp_path / "flightrec-999-0.jsonl").write_text("{not json")
    (tmp_path / "flightrec-998-0.jsonl").write_text(
        '{"other_format": true}\n{"ts": 1}\n')
    (tmp_path / "notes.txt").write_text("unrelated")
    out = events.read_dumps(str(tmp_path))
    assert all(e["pid"] == os.getpid() for e in out)
    assert any(e["kind"] == "ok" for e in out)


def test_dump_is_atomic_no_tmp_left(tmp_path):
    events.record("proc", "x")
    target = str(tmp_path / "dump.jsonl")
    assert events.dump(target, "t") == target
    assert os.listdir(tmp_path) == ["dump.jsonl"]


# ---------------------------------------------------------------------------
# Percentile math vs numpy
# ---------------------------------------------------------------------------


def test_quantiles_from_buckets_vs_numpy():
    """Bucket-interpolated quantiles agree with numpy within one bucket
    width (the estimator's resolution bound)."""
    rng = np.random.default_rng(7)
    samples = np.concatenate([
        rng.uniform(0.0, 2.0, 4000),       # body
        rng.uniform(2.0, 9.5, 1000),       # tail
    ])
    width = 0.05
    bounds = [round(width * i, 6) for i in range(1, 201)]  # 0.05 .. 10.0
    counts = [0] * (len(bounds) + 1)
    for s in samples:
        counts[bisect.bisect_left(bounds, float(s))] += 1
    q = mt.quantiles_from_buckets(bounds, counts, (0.5, 0.95, 0.99),
                                  lo=float(samples.min()),
                                  hi=float(samples.max()))
    for p in (0.5, 0.95, 0.99):
        expect = float(np.percentile(samples, p * 100))
        assert abs(q[p] - expect) <= width + 1e-9, \
            f"p{int(p * 100)}: got {q[p]}, numpy {expect}"


def test_histogram_observe_to_series_quantiles():
    """End to end through the Histogram type: observe() bins, collect()
    snapshots, series_quantiles() interpolates."""
    width = 0.01
    bounds = tuple(round(width * i, 6) for i in range(1, 101))  # .01..1.0
    h = mt.Histogram("obs_test_latency_s", "test", buckets=bounds)
    rng = np.random.default_rng(3)
    samples = rng.uniform(0.0, 1.0, 3000)
    for s in samples:
        h.observe(float(s))
    entry = mt.collect()["obs_test_latency_s"]
    assert entry["type"] == "histogram"
    (series,) = entry["series"]
    assert series["value"]["count"] == len(samples)
    q = mt.series_quantiles(entry, series)
    for p in (0.5, 0.95, 0.99):
        expect = float(np.percentile(samples, p * 100))
        assert abs(q[p] - expect) <= width + 1e-9


def test_quantiles_empty_and_single_bucket():
    nanq = mt.quantiles_from_buckets([1.0, 2.0], [0, 0, 0], (0.5,))
    assert np.isnan(nanq[0.5])
    # All mass in one bucket: clamp to observed min/max range.
    q = mt.quantiles_from_buckets([1.0, 2.0], [0, 5, 0], (0.5, 0.99),
                                  lo=1.2, hi=1.8)
    for v in q.values():
        assert 1.0 <= v <= 2.0


def test_merged_snapshots_quantile_bucket_exact():
    """Quantiles over a merge_snapshot() of two processes' histograms
    equal quantiles over the union of their samples (bucket-exact
    merging is the point of shipping buckets, not summaries)."""
    bounds = tuple(round(0.02 * i, 6) for i in range(1, 51))
    a = mt.Histogram("obs_merge_a_s", "a", buckets=bounds)
    rng = np.random.default_rng(11)
    s1 = rng.uniform(0.0, 0.5, 1000)
    s2 = rng.uniform(0.3, 1.0, 1000)
    for s in s1:
        a.observe(float(s))
    snap1 = {k: v for k, v in mt.collect().items() if k == "obs_merge_a_s"}
    # Second "process": same metric name, different samples.
    for s in s2:
        a.observe(float(s))
    snap_both = {k: v for k, v in mt.collect().items()
                 if k == "obs_merge_a_s"}
    merged = {}
    mt.merge_snapshot(merged, snap1)
    (series,) = merged["obs_merge_a_s"]["series"]
    assert series["value"]["count"] == 1000
    both = np.concatenate([s1, s2])
    (series_b,) = snap_both["obs_merge_a_s"]["series"]
    q = mt.series_quantiles(snap_both["obs_merge_a_s"], series_b)
    assert abs(q[0.5] - float(np.percentile(both, 50))) <= 0.02 + 1e-9


# ---------------------------------------------------------------------------
# Cluster: chaos kill -> crash dump stitched with live peers by trace id,
# plus cli events / cli top smoke against the same live cluster.
# ---------------------------------------------------------------------------


@pytest.fixture
def serve_chaos_cluster(request):
    from ray_tpu._private import fault_injection as fi
    cfg = dict(getattr(request, "param", {}))
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    from ray_tpu import serve
    serve.start()
    try:
        yield info
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        from ray_tpu.serve import _private as sp
        with sp._router_states_lock:
            sp._router_states.clear()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


# `slow`: ~25s for the events-plane half of the replica-kill stitching
# scenario; the spans-plane twin (test_spans.py, which additionally
# gates critical_path reconstruction) keeps the kill in tier-1.
@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize(
    "serve_chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 31,
      # Scripted: every serve replica incarnation dies at its 4th serve
      # event (dispatch + 3 token pulls), mid-generation — same scenario
      # as the fault-tolerance suite's token-exact resume test.
      "chaos_kill_replica_salts": "*",
      "chaos_kill_replica_at": 4,
      "chaos_max_faults": 1}],
    indirect=True)
def test_chaos_kill_events_stitch_by_trace(serve_chaos_cluster):
    """ISSUE acceptance criterion: after a chaos kill of an engine
    replica mid-generation, `state.events()` / `cli events --trace`
    reconstruct the decision sequence by joining the dead replica's
    crash dump with events from surviving processes on one trace id."""
    from ray_tpu import serve, state
    from ray_tpu.scripts import cli

    handle = serve.run(serve.LLMDeployment.options(
        name="llm_obs").bind(model="gpt", config="nano", max_lanes=4,
                             seed=0))
    with tracing.trace("chaos-forensics") as tid:
        got = list(handle.options("generate",
                                  failover=serve.llm_stream_resume)
                   .stream([1, 2, 3], 8))
    assert len(got) == 8

    deadline = time.time() + 20
    evs = []
    while time.time() < deadline:
        evs = state.events(trace_id=tid)
        if any(e.get("source") == "crash" for e in evs) and \
           any(e.get("source") == "live" for e in evs):
            break
        time.sleep(0.5)

    sources = {e.get("source") for e in evs}
    assert "crash" in sources, \
        f"no black-box events for trace {tid}: {evs}"
    assert "live" in sources
    # The dead replica's ring carries the engine-side decisions for this
    # request; the driver's ring carries the serve-side failover.
    kinds = {(e["plane"], e["kind"]) for e in evs}
    assert ("engine", "submit") in kinds
    assert ("serve", "failover") in kinds
    # The kill fired mid-generation: the crashed incarnation and its
    # replacement both submitted, so >= 2 distinct pids share the trace.
    assert len({e.get("pid") for e in evs
                if e["kind"] == "submit"}) >= 2
    # Skew-normalized merge is ordered.
    adj = [e["ts_adj"] for e in evs]
    assert adj == sorted(adj)

    # -- cli events: same reconstruction, rendered ---------------------
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["events", "--address",
                       serve_chaos_cluster["gcs_address"],
                       "--trace", tid])
    assert rc == 0
    out = buf.getvalue()
    assert f"trace={tid[:8]}" in out
    assert "submit" in out and "!" in out  # crash-source marker rendered

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["events", "--address",
                       serve_chaos_cluster["gcs_address"],
                       "--plane", "engine", "--limit", "5", "--json"])
    assert rc == 0
    parsed = json.loads(buf.getvalue())
    assert len(parsed) <= 5
    assert all(e["plane"] == "engine" for e in parsed)

    # -- cli top: per-plane rates + latency percentiles ----------------
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["top", "--address",
                       serve_chaos_cluster["gcs_address"],
                       "--count", "1", "--window", "60"])
    assert rc == 0
    out = buf.getvalue()
    assert "events/s by plane" in out
    assert "latency percentiles:" in out
    # The generation above populated the engine TTFT/TBT histograms.
    assert "p50=" in out and "p99=" in out
