"""Thin-client tests (reference: python/ray/util/client/ — client proxies
all API calls to a server-side driver process)."""

import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def client_cluster():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4})
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "client-server",
         "--address", cluster.address, "--host", "127.0.0.1",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    import os
    os.set_blocking(proc.stdout.fileno(), False)
    port = None
    buf = ""
    deadline = time.time() + 60
    while time.time() < deadline:
        chunk = proc.stdout.read()
        if chunk:
            buf += chunk.decode("utf-8", "replace")
        if "listening on" in buf:
            port = int(buf.split("listening on ")[1].split()[0]
                       .rsplit(":", 1)[1])
            break
        if proc.poll() is not None:
            raise RuntimeError(f"client server died during startup: {buf}")
        time.sleep(0.2)
    assert port, "client server never reported its port"
    ray_tpu.init(address=f"ray_tpu://127.0.0.1:{port}")
    yield cluster
    ray_tpu.shutdown()
    proc.terminate()
    proc.wait(timeout=10)
    cluster.shutdown()


def test_client_put_get_tasks_actors(client_cluster):
    arr = np.arange(1000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)

    @ray_tpu.remote
    def square(x):
        return x * x

    refs = [square.remote(i) for i in range(5)]
    ready, rest = ray_tpu.wait(refs, num_returns=5, timeout=60)
    assert len(ready) == 5 and not rest
    assert ray_tpu.get(refs) == [0, 1, 4, 9, 16]

    # Refs as args cross the client boundary.
    assert ray_tpu.get(square.remote(ray_tpu.put(6))) == 36

    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def add(self, k):
            self.n += k
            return self.n

    c = Counter.remote(100)
    assert ray_tpu.get(c.add.remote(5)) == 105
    assert ray_tpu.get(c.add.remote(5)) == 110
    ray_tpu.kill(c)

    # Errors propagate.
    @ray_tpu.remote
    def boom():
        raise ValueError("client boom")

    with pytest.raises(Exception, match="client boom"):
        ray_tpu.get(boom.remote(), timeout=30)

    # GCS passthrough powers cluster introspection + state API.
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 4
    nodes = ray_tpu.nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]


def test_client_placement_group_and_named_actor(client_cluster):
    """PG API proxies through the server; named actors resolve across
    sessions (reference: client supports the full API surface)."""
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)

    pg = placement_group([{"CPU": 1}])
    assert pg.wait(60)
    remove_placement_group(pg)

    @ray_tpu.remote
    class Named:
        def who(self):
            return "named-one"

    a = Named.options(name="client-named", lifetime="detached").remote()
    ray_tpu.get(a.who.remote())
    h = ray_tpu.get_actor("client-named")
    assert ray_tpu.get(h.who.remote()) == "named-one"
    ray_tpu.kill(h)


def test_client_nested_refs_and_num_returns(client_cluster):
    @ray_tpu.remote
    def unwrap(lst):
        import ray_tpu as rt
        return sum(rt.get(r) for r in lst)

    refs = [ray_tpu.put(i) for i in (1, 2, 3)]
    assert ray_tpu.get(unwrap.remote(refs)) == 6

    @ray_tpu.remote
    class Multi:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return "a", "b"

    m = Multi.remote()
    r1, r2 = m.pair.options(num_returns=2).remote()
    assert ray_tpu.get([r1, r2]) == ["a", "b"]
    ray_tpu.kill(m)


def test_client_returned_ref_roundtrip(client_cluster):
    """A ref RETURNED from a task (never created by this session) still
    resolves through the client."""
    @ray_tpu.remote
    def make_ref():
        import ray_tpu as rt
        return rt.put(41)

    inner = ray_tpu.get(make_ref.remote())
    assert ray_tpu.get(inner, timeout=30) == 41
    ready, _ = ray_tpu.wait([inner], num_returns=1, timeout=30)
    assert ready

    # Top-level ref args auto-dereference (reference semantics)...
    @ray_tpu.remote
    def plus_one(v):
        return v + 1

    assert ray_tpu.get(plus_one.remote(inner)) == 42

    # ...while refs inside containers pass through unresolved.
    @ray_tpu.remote
    def deref(lst):
        import ray_tpu as rt
        return rt.get(lst[0]) + 2

    assert ray_tpu.get(deref.remote([inner])) == 43


def test_client_deep_nested_refs_and_handles(client_cluster):
    """Refs/handles buried inside ARBITRARY user objects translate in
    both directions (reference: client ARCHITECTURE.md deep serializer;
    VERDICT r2 missing 9 — the r3 client only walked plain containers)."""

    class Box:
        def __init__(self, payload):
            self.payload = payload

    @ray_tpu.remote
    def unbox_and_read(box):
        # box.payload["ref"] is a live cluster ref nested in a user object.
        return ray_tpu.get(box.payload["ref"]) + box.payload["k"]

    inner = ray_tpu.put(40)
    out = ray_tpu.get(unbox_and_read.remote(Box({"ref": inner, "k": 2})),
                      timeout=60)
    assert out == 42

    # A task RETURNING refs nested inside a user object: the client gets
    # usable refs back.
    @ray_tpu.remote
    def produce_boxed_refs():
        return Box({"refs": [ray_tpu.put(i * 11) for i in range(3)]})

    box = ray_tpu.get(produce_boxed_refs.remote(), timeout=60)
    assert [ray_tpu.get(r, timeout=60) for r in box.payload["refs"]] \
        == [0, 11, 22]

    # Actor handles inside user objects round-trip too.
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def poke(box):
        return ray_tpu.get(box.payload.incr.remote())

    c = Counter.remote()
    assert ray_tpu.get(poke.remote(Box(c)), timeout=60) == 1
    assert ray_tpu.get(poke.remote(Box(c)), timeout=60) == 2
