"""Data library tests (reference coverage model: python/ray/data/tests/
test_dataset*.py) against a real single-node cluster."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]
    assert ds.num_blocks() == 4


def test_from_items_scalars(cluster):
    ds = rd.from_items([1, 2, 3, 4])
    assert ds.take_all() == [1, 2, 3, 4]
    assert ds.sum() == 10


def test_map_filter_flat_map_fused(cluster):
    ds = (rd.range(20, parallelism=2)
          .map(lambda r: {"id": r["id"] * 2})
          .filter(lambda r: r["id"] % 4 == 0)
          .flat_map(lambda r: [r, r]))
    rows = ds.take_all()
    assert len(rows) == 20  # 10 even-doubled ids, duplicated
    assert all(r["id"] % 4 == 0 for r in rows)


def test_map_batches_numpy(cluster):
    ds = rd.range(10, parallelism=2).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take_all()
    assert rows[3] == {"id": 3, "sq": 9}


def test_map_batches_pandas(cluster):
    def add_col(df):
        df["y"] = df["id"] + 1
        return df

    ds = rd.range(6, parallelism=2).map_batches(add_col,
                                                batch_format="pandas")
    assert ds.take(2) == [{"id": 0, "y": 1}, {"id": 1, "y": 2}]


def test_repartition_and_shuffle(cluster):
    ds = rd.range(50, parallelism=2).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 50

    shuffled = rd.range(50, parallelism=2).random_shuffle(seed=0)
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))


def test_sort(cluster):
    ds = rd.from_items([{"x": v} for v in [5, 3, 9, 1]]).sort("x")
    assert [r["x"] for r in ds.take_all()] == [1, 3, 5, 9]
    ds = rd.from_items([{"x": v} for v in [5, 3, 9, 1]]).sort(
        "x", descending=True)
    assert [r["x"] for r in ds.take_all()] == [9, 5, 3, 1]


def test_limit_and_union(cluster):
    a = rd.range(10, parallelism=2).limit(3)
    assert a.count() == 3
    u = rd.from_items([1, 2]).union(rd.from_items([3, 4]))
    assert sorted(u.take_all()) == [1, 2, 3, 4]


def test_split_for_ingest(cluster):
    shards = rd.range(40, parallelism=4).split(2)
    assert len(shards) == 2
    total = sum(s.count() for s in shards)
    assert total == 40


def test_iter_batches_batching(cluster):
    ds = rd.range(25, parallelism=3)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [10, 10, 5]
    assert isinstance(batches[0]["id"], np.ndarray)
    # drop_last drops the remainder
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10,
                                                   drop_last=True)]
    assert sizes == [10, 10]


def test_groupby(cluster):
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(9)])
    counts = ds.groupby("k").count().take_all()
    assert counts == [{"k": 0, "count()": 3}, {"k": 1, "count()": 3},
                      {"k": 2, "count()": 3}]
    sums = ds.groupby("k").sum("v").take_all()
    assert sums[0]["sum(v)"] == 0 + 3 + 6


def test_aggregates(cluster):
    ds = rd.from_items([{"v": float(i)} for i in range(10)])
    assert ds.sum("v") == 45.0
    assert ds.min("v") == 0.0
    assert ds.max("v") == 9.0
    assert ds.mean("v") == 4.5


def test_parquet_roundtrip(cluster, tmp_path):
    ds = rd.range(30, parallelism=3).map(lambda r: {"id": r["id"],
                                                    "sq": r["id"] ** 2})
    out = str(tmp_path / "pq")
    ds.write_parquet(out)
    back = rd.read_parquet(out)
    assert back.count() == 30
    assert back.sort("id").take(2) == [{"id": 0, "sq": 0}, {"id": 1, "sq": 1}]


def test_csv_and_json_roundtrip(cluster, tmp_path):
    ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).sort("a").take_all() == [
        {"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    ds.write_json(str(tmp_path / "json"))
    assert rd.read_json(str(tmp_path / "json")).sort("a").count() == 2


def test_numpy_tensor_column(cluster):
    arrs = np.arange(12, dtype=np.float32).reshape(4, 3)
    ds = rd.from_numpy(arrs, column="feat")
    rows = ds.take_all()
    assert len(rows) == 4
    assert rows[1]["feat"] == [3.0, 4.0, 5.0]


def test_dataset_to_train_ingest(cluster):
    """Data -> Train handoff: split per worker, iterate numpy batches."""
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import DataParallelTrainer

    shards = rd.range(32, parallelism=4).split(2)

    def loop(config):
        from ray_tpu.train import session
        shard = config["shards"][session.get_world_rank()]
        seen = 0
        for batch in shard.iter_batches(batch_size=8):
            seen += len(batch["id"])
        session.report({"rows": seen})

    result = DataParallelTrainer(
        loop, train_loop_config={"shards": shards},
        scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.error is None
    assert result.metrics["rows"] == 16
