"""Data library tests (reference coverage model: python/ray/data/tests/
test_dataset*.py) against a real single-node cluster."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]
    assert ds.num_blocks() == 4


def test_from_items_scalars(cluster):
    ds = rd.from_items([1, 2, 3, 4])
    assert ds.take_all() == [1, 2, 3, 4]
    assert ds.sum() == 10


def test_map_filter_flat_map_fused(cluster):
    ds = (rd.range(20, parallelism=2)
          .map(lambda r: {"id": r["id"] * 2})
          .filter(lambda r: r["id"] % 4 == 0)
          .flat_map(lambda r: [r, r]))
    rows = ds.take_all()
    assert len(rows) == 20  # 10 even-doubled ids, duplicated
    assert all(r["id"] % 4 == 0 for r in rows)


def test_map_batches_numpy(cluster):
    ds = rd.range(10, parallelism=2).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take_all()
    assert rows[3] == {"id": 3, "sq": 9}


def test_map_batches_pandas(cluster):
    def add_col(df):
        df["y"] = df["id"] + 1
        return df

    ds = rd.range(6, parallelism=2).map_batches(add_col,
                                                batch_format="pandas")
    assert ds.take(2) == [{"id": 0, "y": 1}, {"id": 1, "y": 2}]


def test_repartition_and_shuffle(cluster):
    ds = rd.range(50, parallelism=2).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 50

    shuffled = rd.range(50, parallelism=2).random_shuffle(seed=0)
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))


def test_sort(cluster):
    ds = rd.from_items([{"x": v} for v in [5, 3, 9, 1]]).sort("x")
    assert [r["x"] for r in ds.take_all()] == [1, 3, 5, 9]
    ds = rd.from_items([{"x": v} for v in [5, 3, 9, 1]]).sort(
        "x", descending=True)
    assert [r["x"] for r in ds.take_all()] == [9, 5, 3, 1]


def test_limit_and_union(cluster):
    a = rd.range(10, parallelism=2).limit(3)
    assert a.count() == 3
    u = rd.from_items([1, 2]).union(rd.from_items([3, 4]))
    assert sorted(u.take_all()) == [1, 2, 3, 4]


def test_split_for_ingest(cluster):
    shards = rd.range(40, parallelism=4).split(2)
    assert len(shards) == 2
    total = sum(s.count() for s in shards)
    assert total == 40


def test_iter_batches_batching(cluster):
    ds = rd.range(25, parallelism=3)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [10, 10, 5]
    assert isinstance(batches[0]["id"], np.ndarray)
    # drop_last drops the remainder
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10,
                                                   drop_last=True)]
    assert sizes == [10, 10]


def test_groupby(cluster):
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(9)])
    counts = ds.groupby("k").count().take_all()
    assert counts == [{"k": 0, "count()": 3}, {"k": 1, "count()": 3},
                      {"k": 2, "count()": 3}]
    sums = ds.groupby("k").sum("v").take_all()
    assert sums[0]["sum(v)"] == 0 + 3 + 6


def test_aggregates(cluster):
    ds = rd.from_items([{"v": float(i)} for i in range(10)])
    assert ds.sum("v") == 45.0
    assert ds.min("v") == 0.0
    assert ds.max("v") == 9.0
    assert ds.mean("v") == 4.5


def test_parquet_roundtrip(cluster, tmp_path):
    ds = rd.range(30, parallelism=3).map(lambda r: {"id": r["id"],
                                                    "sq": r["id"] ** 2})
    out = str(tmp_path / "pq")
    ds.write_parquet(out)
    back = rd.read_parquet(out)
    assert back.count() == 30
    assert back.sort("id").take(2) == [{"id": 0, "sq": 0}, {"id": 1, "sq": 1}]


def test_csv_and_json_roundtrip(cluster, tmp_path):
    ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).sort("a").take_all() == [
        {"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    ds.write_json(str(tmp_path / "json"))
    assert rd.read_json(str(tmp_path / "json")).sort("a").count() == 2


def test_numpy_tensor_column(cluster):
    arrs = np.arange(12, dtype=np.float32).reshape(4, 3)
    ds = rd.from_numpy(arrs, column="feat")
    rows = ds.take_all()
    assert len(rows) == 4
    assert rows[1]["feat"] == [3.0, 4.0, 5.0]


def test_dataset_to_train_ingest(cluster):
    """Data -> Train handoff: split per worker, iterate numpy batches."""
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import DataParallelTrainer

    shards = rd.range(32, parallelism=4).split(2)

    def loop(config):
        from ray_tpu.train import session
        shard = config["shards"][session.get_world_rank()]
        seen = 0
        for batch in shard.iter_batches(batch_size=8):
            seen += len(batch["id"])
        session.report({"rows": seen})

    result = DataParallelTrainer(
        loop, train_loop_config={"shards": shards},
        scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.error is None
    assert result.metrics["rows"] == 16


def test_streaming_split_feeds_actors(cluster):
    """streaming_split: each consumer actor iterates its own shard stream
    without driver round-trips (reference: dataset.streaming_split)."""
    import ray_tpu
    from ray_tpu import data as rdata

    ds = rdata.range(1000).map(lambda r: {"id": r["id"], "v": r["id"] * 2})
    its = ds.streaming_split(2, equal=True)
    assert len(its) == 2

    @ray_tpu.remote
    class Consumer:
        def consume(self, it):
            total_rows = 0
            total_v = 0
            for batch in it.iter_batches(batch_size=128):
                total_rows += len(batch["id"])
                total_v += int(batch["v"].sum())
            return total_rows, total_v

    consumers = [Consumer.remote() for _ in range(2)]
    results = ray_tpu.get([c.consume.remote(it)
                           for c, it in zip(consumers, its)])
    assert sum(r for r, _ in results) == 1000
    assert sum(v for _, v in results) == sum(i * 2 for i in range(1000))
    for c in consumers:
        ray_tpu.kill(c)


def test_map_batches_actor_pool_caches_state(cluster):
    """compute=ActorPoolStrategy: a CLASS transform constructs once per
    pool actor and is reused across blocks (reference:
    actor_pool_map_operator.py)."""
    from ray_tpu import data as rdata

    class Stateful:
        def __init__(self):
            import uuid
            self.token = uuid.uuid4().hex  # expensive model load stand-in

        def __call__(self, batch):
            batch["token"] = np.array([self.token] * len(batch["id"]))
            return batch

    ds = rdata.range(400).repartition(8).map_batches(
        Stateful, compute=rdata.ActorPoolStrategy(size=2, num_cpus=0.5))
    rows = ds.take_all()
    assert len(rows) == 400
    tokens = {r["token"] for r in rows}
    # 8 blocks through a 2-actor pool: state constructed at most twice.
    assert 1 <= len(tokens) <= 2


def test_util_actor_pool_and_queue(cluster):
    """ray_tpu.util.ActorPool + distributed Queue (reference:
    ray/util/actor_pool.py, ray/util/queue.py)."""
    import ray_tpu
    from ray_tpu.util import ActorPool, Queue

    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            return x * x

    pool = ActorPool([Sq.remote() for _ in range(2)])
    assert list(pool.map(lambda a, v: a.sq.remote(v), range(6))) == \
        [0, 1, 4, 9, 16, 25]
    assert sorted(pool.map_unordered(
        lambda a, v: a.sq.remote(v), range(4))) == [0, 1, 4, 9]

    q = Queue(maxsize=2)
    q.put("a")
    q.put("b")
    import pytest as _pytest
    from ray_tpu.util.queue import Empty, Full
    with _pytest.raises(Full):
        q.put("c", timeout=0.2)
    assert q.get() == "a"
    assert q.get() == "b"
    with _pytest.raises(Empty):
        q.get(timeout=0.2)

    # Producer/consumer across actors (queue handle is picklable).
    @ray_tpu.remote
    class Producer:
        def run(self, q, n):
            for i in range(n):
                q.put(i)
            return True

    p = Producer.remote()
    ref = p.run.remote(q, 5)
    got = [q.get(timeout=30) for _ in range(5)]
    assert got == list(range(5))
    assert ray_tpu.get(ref) is True
    q.shutdown()
    ray_tpu.kill(p)


def test_preprocessors(cluster):
    """Preprocessor fit/transform + chain + serving-path transform_batch
    (reference: data/preprocessor.py + preprocessors/)."""
    from ray_tpu import data as rdata
    from ray_tpu.data import (
        Chain, Concatenator, LabelEncoder, MinMaxScaler, StandardScaler)

    ds = rdata.from_items([
        {"a": float(i), "b": float(i * 2), "label": ["x", "y"][i % 2]}
        for i in range(100)])

    scaler = StandardScaler(["a"]).fit(ds)
    out = scaler.transform(ds).take_all()
    vals = np.array([r["a"] for r in out])
    assert abs(vals.mean()) < 1e-6 and abs(vals.std() - 1.0) < 0.02

    chain = Chain(MinMaxScaler(["a", "b"]), LabelEncoder("label"),
                  Concatenator(["a", "b"])).fit(ds)
    rows = chain.transform(ds).take_all()
    assert np.asarray(rows[0]["features"]).shape == (2,)
    assert set(r["label"] for r in rows) == {0, 1}
    feats = np.array([r["features"] for r in rows])
    assert feats.min() >= 0.0 and feats.max() <= 1.0

    # Serving path: one batch, no dataset.
    batch = chain.transform_batch(
        {"a": np.array([0.0, 99.0]), "b": np.array([0.0, 198.0]),
         "label": np.array(["x", "y"])})
    assert batch["features"].shape == (2, 2)
    assert batch["features"][1, 0] == 1.0


def test_dataset_pipeline_repeat_and_window(cluster):
    """repeat(n).iter_epochs re-executes the plan per epoch (fresh
    shuffles); window(k) bounds per-window blocks (reference:
    dataset_pipeline.py)."""
    from ray_tpu import data as rdata

    ds = rdata.range(64).repartition(8).random_shuffle()
    pipe = ds.repeat(3)
    orders = []
    for epoch_ds in pipe.iter_epochs():
        orders.append(tuple(r["id"] for r in epoch_ds.take_all()))
    assert len(orders) == 3
    assert all(sorted(o) == list(range(64)) for o in orders)
    # Fresh executions -> epochs shuffle independently.
    assert len(set(orders)) > 1

    windows = list(rdata.range(64).repartition(8)
                   .window(blocks_per_window=2).iter_windows())
    assert len(windows) == 4
    total = sum(w.count() for w in windows)
    assert total == 64

    # Batch streaming across epochs.
    n = sum(len(b["id"]) for b in
            rdata.range(10).repeat(2).iter_batches(batch_size=4))
    assert n == 20


def test_read_sql_sqlite(cluster, tmp_path):
    """read_sql over a stdlib sqlite3 database (reference:
    datasource/sql_datasource.py)."""
    import sqlite3
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE items (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO items VALUES (?, ?)",
                     [(i, f"n{i}") for i in range(20)])
    conn.commit()
    conn.close()

    ds = rd.read_sql("SELECT id, name FROM items ORDER BY id",
                        lambda: sqlite3.connect(db), parallelism=4)
    rows = ds.take_all()
    assert len(rows) == 20
    assert rows[0] == {"id": 0, "name": "n0"}
    assert rows[19]["name"] == "n19"


def test_read_images(cluster, tmp_path):
    from PIL import Image
    for i in range(3):
        Image.new("RGB", (8 + i, 8), color=(i * 10, 0, 0)).save(
            tmp_path / f"img{i}.png")
    ds = rd.read_images(str(tmp_path / "*.png"), size=(8, 8))
    rows = ds.take_all()
    assert len(rows) == 3
    img = np.asarray(rows[0]["image"]).reshape(8, 8, 3)
    assert img.min() >= 0 and img.max() <= 255


def test_read_webdataset(cluster, tmp_path):
    import io
    import tarfile
    shard = tmp_path / "shard-000.tar"
    with tarfile.open(shard, "w") as tar:
        for key in ("a", "b"):
            for ext, payload in (("txt", f"text-{key}".encode()),
                                 ("cls", b"7")):
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
    ds = rd.read_webdataset(str(shard))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert rows[0]["__key__"] == "a"
    assert bytes(rows[0]["txt"]) == b"text-a"
    assert bytes(rows[1]["cls"]) == b"7"


def test_streaming_spans_all_operators(cluster, tmp_path):
    """Streaming executes ACROSS operators with bounded windows: with a
    small window, consuming the first outputs of a 3-stage pipeline must
    not have pushed every input block through stage 1 (VERDICT r2 weak 6:
    pre-barrier segments used to launch their whole input up front)."""
    import os

    import numpy as np

    from ray_tpu.data.executor import (
        ActorPoolStrategy,
        ExecPlan,
        OneToOne,
        iter_output_refs,
    )

    marks = str(tmp_path / "marks")
    os.makedirs(marks, exist_ok=True)
    n_blocks = 16

    def stage1(block):
        # Touch a per-block marker so the test can count stage-1 progress.
        open(os.path.join(marks, f"{int(block[0])}"), "w").close()
        return block + 1

    def stage2(block):
        return block * 2

    refs = [ray_tpu.put(np.full(4, float(i * 100))) for i in range(n_blocks)]
    plan = ExecPlan(refs, [
        OneToOne(stage1, "stage1"),
        # The actor-pool stage splits fusion -> 3 genuine pipeline stages.
        OneToOne(stage2, "stage2", compute=ActorPoolStrategy(size=1)),
        OneToOne(lambda b: b - 1, "stage3"),
    ])
    it = iter_output_refs(plan, window=2)
    first = ray_tpu.get(next(it), timeout=120)
    np.testing.assert_array_equal(first, np.full(4, 1.0))  # (0+1)*2-1
    done_stage1 = len(os.listdir(marks))
    assert done_stage1 < n_blocks, (
        f"stage 1 ran {done_stage1}/{n_blocks} blocks before the first "
        f"output was consumed — no cross-operator backpressure")
    # Draining yields every block, in order.
    rest = [ray_tpu.get(r, timeout=120) for r in it]
    assert len(rest) == n_blocks - 1
    np.testing.assert_array_equal(
        rest[-1], np.full(4, ((n_blocks - 1) * 100 + 1) * 2 - 1.0))


# ---------------------------------------------------------------------------
# Logical plan + optimizer (reference: data/_internal/logical/optimizers.py)
# ---------------------------------------------------------------------------


def _write_parts(tmp_path, n_files=8, rows=100):
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path / "parts"
    d.mkdir(exist_ok=True)
    for i in range(n_files):
        t = pa.table({"a": list(range(i * rows, (i + 1) * rows)),
                      "b": [float(x) for x in range(rows)],
                      "c": ["x"] * rows})
        pq.write_table(t, str(d / f"p-{i:03d}.parquet"))
    return str(d)


def test_limit_pushdown_reads_fewer_blocks(cluster, tmp_path):
    """read_parquet(...).limit(n) launches read tasks for only the file
    prefix covering n rows (row counts from Parquet METADATA)."""
    from ray_tpu import data as rdata
    path = _write_parts(tmp_path, n_files=8, rows=100)
    ds = rdata.read_parquet(path).limit(150)
    refs, _stages = ds._plan.resolve()
    assert len(refs) == 2, f"expected 2 of 8 files read, got {len(refs)}"
    assert ds.count() == 150
    # Plan inspection shows the decision without executing.
    assert "pushed limit 150" in rdata.read_parquet(path).limit(150).explain()
    # A row-preserving map between read and limit keeps the rule valid...
    ds2 = rdata.read_parquet(path).map(lambda r: r).limit(150)
    refs2, _ = ds2._plan.resolve()
    assert len(refs2) == 2
    # ...but a filter blocks it (it changes row counts).
    ds3 = rdata.read_parquet(path).filter(lambda r: True).limit(150)
    refs3, _ = ds3._plan.resolve()
    assert len(refs3) == 8


def test_projection_pushdown_into_parquet(cluster, tmp_path):
    """select_columns directly after read_parquet reads only those
    columns from disk."""
    import ray_tpu
    from ray_tpu import data as rdata
    path = _write_parts(tmp_path, n_files=3, rows=50)
    ds = rdata.read_parquet(path).select_columns(["a"])
    refs, stages = ds._plan.resolve()
    assert not stages            # the projection moved into the reader
    block = ray_tpu.get(refs[0])
    assert block.column_names == ["a"]
    assert "pushed projection ['a']" in \
        rdata.read_parquet(path).select_columns(["a"]).explain()
    assert ds.count() == 150


def test_read_parallelism_hint_groups_files(cluster, tmp_path):
    from ray_tpu import data as rdata
    path = _write_parts(tmp_path, n_files=9, rows=10)
    ds = rdata.read_parquet(path, parallelism=3)
    refs, _ = ds._plan.resolve()
    assert len(refs) == 3
    assert ds.count() == 90
