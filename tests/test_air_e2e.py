"""AIR end-to-end: Data preprocessing -> Train -> Serve with ResNet
(BASELINE.md e2e target "Data preprocessing → Train → Serve, ResNet-50
ImageNet" — scaled to a synthetic 32x32 dataset and resnet18 on the
virtual CPU mesh; the pipeline shape, not the dataset, is the target)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


def test_air_data_train_serve_resnet(cluster):
    from ray_tpu import data as rdata
    from ray_tpu import serve, train
    from ray_tpu.air import Checkpoint, ScalingConfig
    from ray_tpu.train import session

    # ---- Data: synthetic labeled images + preprocessing map ----
    n = 128

    def make_row(r):
        rng = np.random.default_rng(int(r["id"]))
        label = int(r["id"]) % 10
        # Class-dependent mean keeps the task learnable.
        img = rng.normal(loc=label / 10.0, size=(32, 32, 3))
        return {"image": (img * 127).astype(np.int16), "label": label}

    ds = (rdata.range(n, parallelism=4)
          .map(make_row)
          .map(lambda r: {"image":
                          np.asarray(r["image"], np.float32) / 127.0,
                          "label": r["label"]}))

    # ---- Train: JaxTrainer over the dataset shard, checkpoint params ----
    def loop(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import resnet

        cfg = resnet.ResNetConfig(stage_sizes=(1, 1), width=8,
                                  num_groups=4)
        init_state, train_step = resnet.make_train_step(
            cfg, optax.adam(3e-3))
        state = init_state(jax.random.key(0))
        step = jax.jit(train_step, donate_argnums=0)
        shard = session.get_dataset_shard("train")
        m = {}
        for epoch in range(config["epochs"]):
            for batch in shard.iter_batches(batch_size=32):
                # Tensor columns batch as [rows, flattened]; restore HWC.
                images = jnp.asarray(
                    np.asarray(batch["image"], np.float32)
                    .reshape(-1, 32, 32, 3))
                labels = jnp.asarray(np.asarray(batch["label"]))
                state, m = step(state, {"images": images,
                                        "labels": labels})
        params = jax.device_get(state["params"])
        session.report(
            {"loss": float(m["loss"]), "accuracy": float(m["accuracy"])},
            checkpoint=Checkpoint.from_dict({"params": params}))

    trainer = train.JaxTrainer(
        loop, train_loop_config={"epochs": 6},
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1}),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 2.0, result.metrics
    ckpt = result.checkpoint
    assert ckpt is not None

    # ---- Serve: deployment loads the checkpoint and predicts ----
    @serve.deployment(name="resnet-clf")
    class Classifier:
        def __init__(self, ckpt_dict):
            import jax

            from ray_tpu.models import resnet
            cfg = resnet.ResNetConfig(stage_sizes=(1, 1), width=8,
                                      num_groups=4)
            _, self.apply = resnet.make_model(cfg)
            self.params = jax.device_put(ckpt_dict["params"])
            self._jit = jax.jit(self.apply)

        def __call__(self, image):
            import jax.numpy as jnp
            logits = self._jit(self.params,
                               jnp.asarray(image)[None])
            return int(np.argmax(np.asarray(logits)[0]))

    handle = serve.run(Classifier.bind(ckpt.to_dict()))
    # Predictions for training-distribution images come back as labels.
    sample = make_row({"id": 3})
    pred = handle.remote(
        sample["image"].astype(np.float32) / 127.0).result(timeout=120)
    assert isinstance(pred, int) and 0 <= pred < 10
    serve.delete("resnet-clf")
