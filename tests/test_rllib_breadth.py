"""Exploration strategies, connectors, and the external-env policy
server (reference: rllib/utils/exploration/, rllib/connectors/,
rllib/env/policy_server_input.py + policy_client.py)."""

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (
    ClipActions,
    ConnectorPipeline,
    FlattenObs,
    NormalizeObs,
    UnsquashActions,
)
from ray_tpu.rllib.exploration import (
    EpsilonGreedy,
    GaussianNoise,
    LinearSchedule,
    OrnsteinUhlenbeckNoise,
    PiecewiseSchedule,
    Random,
)


def test_schedules():
    s = LinearSchedule(1.0, 0.0, 100)
    assert s(0) == 1.0 and s(50) == 0.5 and s(1000) == 0.0
    p = PiecewiseSchedule([(0, 0.0), (10, 1.0), (20, 0.5)])
    assert p(0) == 0.0 and p(5) == 0.5 and p(15) == 0.75 and p(99) == 0.5


def test_epsilon_greedy_respects_schedule():
    rng = np.random.default_rng(0)
    eg = EpsilonGreedy(4, initial=1.0, final=0.0, horizon=100)
    base = np.zeros(2000, np.int64)
    # t=0: fully random -> ~75% of actions differ from 0.
    out = eg.apply(base, 0, rng)
    assert (out != 0).mean() > 0.5
    # past horizon: greedy passthrough.
    out = eg.apply(base, 10_000, rng)
    assert (out == 0).all()


def test_gaussian_and_ou_noise_bounded():
    rng = np.random.default_rng(0)
    a = np.zeros((64, 2), np.float32)
    g = GaussianNoise(-1.0, 1.0, scale=0.5)
    out = g.apply(a, 0, rng)
    assert out.min() >= -1.0 and out.max() <= 1.0 and np.abs(out).sum() > 0
    ou = OrnsteinUhlenbeckNoise(-1.0, 1.0)
    o1 = ou.apply(a, 0, rng)
    o2 = ou.apply(a, 1, rng)
    # Temporally correlated: consecutive noise states are closer than
    # independent draws would be.
    assert np.abs(o2 - o1).mean() < np.abs(o1).mean() + 0.5
    r = Random(num_actions=3)
    assert set(np.unique(r.apply(np.zeros(500), 0, rng))) <= {0, 1, 2}


def test_connector_pipeline_and_filters():
    pipe = ConnectorPipeline([FlattenObs()])
    obs = np.ones((5, 3, 2), np.float32)
    assert pipe(obs).shape == (5, 6)
    norm = NormalizeObs()
    rng = np.random.default_rng(0)
    for _ in range(50):
        norm(rng.normal(5.0, 2.0, size=(32, 3)))
    out = norm(rng.normal(5.0, 2.0, size=(1000, 3)))
    assert abs(out.mean()) < 0.2 and 0.7 < out.std() < 1.3
    # Filter state travels (remote workers must normalize identically).
    st = norm.get_state()
    norm2 = NormalizeObs(update=False)
    norm2.set_state(st)
    np.testing.assert_allclose(norm(np.ones((1, 3)) * 5, ),
                               norm2(np.ones((1, 3)) * 5), atol=0.05)
    assert ClipActions(-1, 1)(np.array([3.0, -3.0])).tolist() == [1.0, -1.0]
    np.testing.assert_allclose(
        UnsquashActions(0.0, 10.0)(np.array([-1.0, 0.0, 1.0])),
        [0.0, 5.0, 10.0])


def test_rollout_worker_with_exploration_and_connectors():
    from ray_tpu.rllib.rollout_worker import RolloutWorker

    w = RolloutWorker(
        "CartPole-v1", num_envs=4, rollout_fragment_length=8,
        exploration=EpsilonGreedy(2, initial=1.0, final=1.0, horizon=1),
        obs_connector=NormalizeObs())
    batch, _ = w.sample()
    assert batch["obs"].shape == (32, 4)
    # Fully-random epsilon: both actions appear.
    assert set(np.unique(batch["actions"])) == {0, 1}


def test_policy_server_external_env_roundtrip():
    """An external process-style loop drives episodes via the HTTP
    client; the server accumulates GAE-postprocessed batches a PPO
    learner consumes (reference: policy_server_input.py role)."""
    from ray_tpu.rllib.learner import JaxLearner, ppo_loss
    from ray_tpu.rllib.policy_server import PolicyClient, PolicyServer

    server = PolicyServer(4, 2, seed=0)
    try:
        client = PolicyClient(server.address)
        rng = np.random.default_rng(0)
        for _ in range(3):
            eid = client.start_episode()
            obs = rng.normal(size=4)
            for _t in range(10):
                a = client.get_action(eid, obs)
                assert a in (0, 1)
                client.log_returns(eid, 1.0 if a == 0 else 0.0)
                obs = rng.normal(size=4)
            client.end_episode(eid, obs)
        got = server.to_sample_batch(min_rows=30)
        assert got is not None
        batch, returns = got
        assert batch.count == 30 and len(returns) == 3
        assert set(batch) >= {"obs", "actions", "action_logp",
                              "advantages", "value_targets"}
        # The drained batch trains a learner; weights flow back.
        learner = JaxLearner(4, 2, loss_fn=ppo_loss,
                             config={"lr": 1e-3, "num_sgd_iter": 2,
                                     "sgd_minibatch_size": 16})
        metrics = learner.update(batch)
        assert "total_loss" in metrics
        server.set_weights(learner.get_weights())
        assert server.to_sample_batch(min_rows=1) is None  # drained
    finally:
        server.stop()
