"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analogue is the
in-process multi-node Cluster fixture, python/ray/cluster_utils.py:99): JAX on
CPU with xla_force_host_platform_device_count=8 stands in for an 8-chip TPU
slice, so every sharding/collective path is exercised without TPU hardware.
"""

import os
import sys

# Must be set before jax is imported anywhere.  Force cpu even if the outer
# environment selects a TPU platform — tests exercise shardings on the
# virtual mesh; real-chip runs go through bench.py.  The env var alone is
# not enough here: the image's sitecustomize registers a TPU PJRT plugin at
# interpreter start, so also flip the jax config knob.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    # Learning-regression gates (minutes each on a small host) carry
    # @pytest.mark.slow; `-m "not slow"` is the fast iteration suite,
    # a plain `pytest tests/` still runs everything (reference: test
    # size tags, SURVEY §4).
    config.addinivalue_line(
        "markers", "slow: long learning-gate tests (deselect with "
        "-m 'not slow')")
    config.addinivalue_line(
        "markers", "examples: executes the committed examples/ scripts "
        "as subprocesses (select with -m examples)")
    config.addinivalue_line(
        "markers", "chaos: seeded fault-injection scenarios "
        "(tests/test_fault_tolerance.py); fast cases run in tier-1, "
        "long soaks also carry `slow`")


@pytest.fixture
def tmp_store(tmp_path):
    from ray_tpu._private.object_store import ObjectStore

    store = ObjectStore.create(str(tmp_path / "store.shm"), 16 << 20)
    yield store
    store.close()
