"""Fault-tolerance / chaos suite (reference: python/ray/tests/test_failure*).

Every cluster scenario here runs with the deterministic fault-injection
layer (`ray_tpu/_private/fault_injection.py`): faults are drawn from a
seed, so a failing case replays identically under the same
`chaos_seed`.  Scenarios covered:

1. worker killed mid-task           -> task retry succeeds
2. actor killed mid-stream          -> restart preserves call ordering
3. N% of RPCs dropped               -> cluster converges via retries
4. object copy lost                 -> lineage reconstruction rebuilds it
plus unit tests for schedule determinism and RpcClient retry/backoff.
"""

import asyncio
import socket
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.rpc import EventLoopThread, RpcClient, RpcServer

pytestmark = pytest.mark.chaos


@pytest.fixture
def chaos_cluster(request):
    """One fresh single-node cluster per scenario, torn down with the
    chaos controller and config cache reset (each scenario sets its own
    `_system_config` via indirect parametrization)."""
    cfg = dict(request.param)
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    try:
        yield info
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


# ---------------------------------------------------------------------------
# Determinism of the injected-fault schedule
# ---------------------------------------------------------------------------

def test_chaos_schedule_deterministic():
    """Same seed -> identical fault schedule across two runs; different
    seed -> different schedule (acceptance criterion)."""
    def run(seed):
        c = fi.ChaosController(seed, salt="")
        for _ in range(300):
            c.should("rpc", 0.25, "drop")
        for _ in range(100):
            c.should("native", 0.25, "drop")
        return list(c.schedule)

    s1, s2 = run(42), run(42)
    assert s1 == s2
    assert len(s1) > 0
    assert run(7) != s1


def test_chaos_draw_pure_function():
    """Draws depend only on (seed, salt, plane, index) — not on call
    order or interleaving."""
    a = fi.ChaosController(9, salt="x")
    b = fi.ChaosController(9, salt="x")
    fwd = [a.draw("rpc", i) for i in range(50)]
    rev = [b.draw("rpc", i) for i in reversed(range(50))]
    assert fwd == list(reversed(rev))
    # Salt decorrelates processes sharing a seed.
    c = fi.ChaosController(9, salt="y")
    assert [c.draw("rpc", i) for i in range(50)] != fwd


def test_chaos_max_faults_budget():
    c = fi.ChaosController(3, max_faults=5, salt="")
    for _ in range(500):
        c.should("rpc", 1.0, "drop")
    assert c.faults_injected == 5
    assert len(c.schedule) == 5


# ---------------------------------------------------------------------------
# RpcClient retry with backoff + deadline
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_rpc_retry_transient_server_outage():
    """A call issued while the server is down succeeds once the server
    comes up, without surfacing an error (acceptance criterion)."""
    port = _free_port()
    io = EventLoopThread("test-rpc-retry")
    server = RpcServer()
    received = []

    async def echo(req):
        received.append(req)
        return {"echo": req["x"]}

    server.register("Test", "Echo", echo)

    def start_late():
        time.sleep(0.8)
        io.run(server.start(port))

    t = threading.Thread(target=start_late, daemon=True)
    t.start()
    client = RpcClient(f"127.0.0.1:{port}")
    # Enough backoff budget to span the outage (default 4 retries can
    # complete inside the 0.8s window).
    GLOBAL_CONFIG.apply_system_config({"rpc_max_retries": 10})
    try:
        reply = io.run(client.call("Test", "Echo", {"x": 41}, timeout=15))
        assert reply == {"echo": 41}
        assert received == [{"x": 41}]
    finally:
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG.invalidate_cache()
        t.join()
        io.run(client.close())
        io.run(server.stop())
        io.stop()


def test_rpc_deadline_enforced_across_retries():
    """`timeout` bounds the WHOLE call, retries included: against a
    never-up server the call fails within the deadline, not after
    rpc_max_retries * per-attempt timeouts."""
    port = _free_port()
    io = EventLoopThread("test-rpc-deadline")
    client = RpcClient(f"127.0.0.1:{port}")
    t0 = time.monotonic()
    try:
        with pytest.raises(Exception):
            io.run(client.call("Test", "Echo", {}, timeout=1.5))
        assert time.monotonic() - t0 < 6.0
    finally:
        io.run(client.close())
        io.stop()


def test_rpc_chaos_drop_retried_transparently():
    """Injected chaos drops on the client are absorbed by the retry
    loop: the caller sees only the successful reply."""
    io = EventLoopThread("test-rpc-chaos")
    server = RpcServer()

    async def ping(req):
        return {"pong": True}

    server.register("Test", "Ping", ping)
    port = io.run(server.start(0))
    client = RpcClient(f"127.0.0.1:{port}")
    GLOBAL_CONFIG.apply_system_config({
        "chaos_enabled": True, "chaos_seed": 11,
        "chaos_rpc_drop": 0.5, "chaos_max_faults": 20})
    fi.reset()
    try:
        for _ in range(20):
            assert io.run(client.call("Test", "Ping", {}, timeout=30)) \
                == {"pong": True}
        chaos = fi.get_chaos()
        assert chaos is not None and chaos.faults_injected > 0
    finally:
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()
        io.run(client.close())
        io.run(server.stop())
        io.stop()


# ---------------------------------------------------------------------------
# Scenario 1: worker killed mid-task -> retry succeeds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 1234,
      # Scripted kills: the first three spawned workers die right before
      # their first task execution; their replacements (ordinals 4+) run
      # normally.  Deterministic and convergent by construction.
      "chaos_kill_worker_salts": "1,2,3"}],
    indirect=True)
def test_worker_killed_mid_task_retry_succeeds(chaos_cluster):
    @ray_tpu.remote(max_retries=6)
    def square(x):
        return x * x

    refs = [square.remote(i) for i in range(6)]
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(6)]


# ---------------------------------------------------------------------------
# Scenario 2: actor killed -> restart preserves ordering
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 77,
      # The actor's worker dies before its 4th execution (__init__ is
      # execution 0, so after serving 3 method calls); the restarted
      # incarnation (a fresh ordinal) serves the rest.
      "chaos_kill_worker_salts": "1",
      "chaos_kill_worker_at": 4}],
    indirect=True)
def test_actor_killed_restart_preserves_ordering(chaos_cluster):
    @ray_tpu.remote(max_restarts=2, max_task_retries=-1)
    class Log:
        def __init__(self):
            self.items = []

        def append(self, i):
            self.items.append(i)
            return list(self.items)

    log = Log.remote()
    refs = [log.append.remote(i) for i in range(10)]
    results = ray_tpu.get(refs, timeout=120)
    # Each reply snapshots the actor log at execution time.  Ordering is
    # preserved iff every snapshot is (a) in submission order internally
    # and (b) ends with its own call's index — a reordered or replayed
    # call would break one of the two even across the restart's state
    # reset.
    for i, snap in enumerate(results):
        assert snap[-1] == i
        assert snap == sorted(snap)
    # The suffix executed by the final incarnation is contiguous.
    final = results[-1]
    assert final == list(range(10 - len(final), 10))


# ---------------------------------------------------------------------------
# Scenario 3: drop N% of RPCs -> cluster converges
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 2024,
      "chaos_rpc_drop": 0.15, "chaos_max_faults": 60}],
    indirect=True)
def test_rpc_drop_percentage_cluster_converges(chaos_cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    # Chained submissions exercise lease RPCs, pushes, and result
    # resolution — all through the lossy client layer (every daemon and
    # worker inherits the chaos flags via the env).
    refs = [add.remote(i, i) for i in range(24)]
    assert ray_tpu.get(refs, timeout=180) == [2 * i for i in range(24)]
    total = ray_tpu.get(add.remote(ray_tpu.put(20), 22), timeout=60)
    assert total == 42


# ---------------------------------------------------------------------------
# Scenario 4: object copy lost -> lineage rebuilds it
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 5}],
    indirect=True)
def test_object_loss_lineage_reconstruction(chaos_cluster):
    import numpy as np

    @ray_tpu.remote(max_retries=3)
    def produce(n):
        return np.full(n, 7, dtype=np.int64)

    # Big enough to live in the shared-memory store (not inline).
    ref = produce.remote(1 << 17)
    first = ray_tpu.get(ref, timeout=60)
    assert int(first.sum()) == 7 * (1 << 17)

    # Destroy the only copy behind the owner's back, as a node loss
    # would: delete it from the node store via the daemon.
    from ray_tpu import api as _api
    cw = _api._worker
    cw.io.run(cw.pool.get(cw.hostd_address).call(
        "NodeManager", "FreeObject", {"id": ref.id.binary()}))

    again = ray_tpu.get(ref, timeout=120)
    assert int(again.sum()) == 7 * (1 << 17)
    # The producing task's retry budget paid for exactly one resubmit.
    pending = cw.tasks.get(ref.id.task_id())
    if pending is not None:
        assert pending.retries_left == 2


# ---------------------------------------------------------------------------
# Scenario 4b: batched dispatch under faults — a mid-batch worker death
# fails only the tasks routed to that worker; a preempting hostd rejects
# the whole batch cleanly, per task.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("chaos_cluster", [{}], indirect=True)
def test_worker_killed_mid_batch_only_its_tasks_retry(chaos_cluster):
    """SIGKILL one worker while a batched burst executes: the dead
    incarnation's tasks are resubmitted via lineage (their results come
    from other pids), every other task completes exactly once on its
    original worker, and no task is lost or duplicated."""
    import os
    import signal

    @ray_tpu.remote(max_retries=4)
    def slow(i):
        time.sleep(1.0)
        return (os.getpid(), i)

    n = 24
    refs = [slow.remote(i) for i in range(n)]
    # Pick the victim from the hostd's live worker table the moment a
    # lease lands: a leased worker is then at most a poll interval into
    # its first 1.0s task, so the kill is guaranteed mid-execution.
    from ray_tpu import api as _api
    cw = _api._worker
    leased: list = []
    deadline = time.monotonic() + 30.0
    while not leased and time.monotonic() < deadline:
        table = cw.io.run(cw.pool.get(cw.hostd_address).call(
            "NodeManager", "ListWorkers", {}))
        leased = [w["pid"] for w in table["workers"]
                  if w["state"] == "leased" and w["alive"]]
        if not leased:
            time.sleep(0.05)
    assert leased, "no lease landed within 30s"
    victim = leased[0]
    os.kill(victim, signal.SIGKILL)
    out = ray_tpu.get(refs, timeout=120)

    # Exactly-once per task: the incarnation guard means a retried task
    # cannot double-deliver even if the dead worker's seal raced the kill.
    assert sorted(i for _, i in out) == list(range(n))
    # No task slept out its 1.0s on the victim before the 0.3s kill, so
    # every result must come from a LIVE incarnation...
    assert victim not in {p for p, _ in out}
    # ...while the surviving workers kept executing their share.
    assert len({p for p, _ in out}) >= 2


@pytest.mark.parametrize("chaos_cluster", [{}], indirect=True)
def test_preempting_hostd_rejects_batch_cleanly(chaos_cluster):
    """A hostd that has received a preemption notice rejects a batched
    lease request whole: every task in the burst gets its own clean
    scheduling failure naming the reason — no partial grants, no hang."""
    from ray_tpu import api as _api
    from ray_tpu.exceptions import WorkerCrashedError

    cw = _api._worker
    cw.io.run(cw.pool.get(cw.hostd_address).call(
        "NodeManager", "NotifyPreemption", {"grace_s": 300.0}))

    @ray_tpu.remote(max_retries=0)
    def doomed(i):
        return i

    refs = [doomed.remote(i) for i in range(12)]
    for r in refs:
        with pytest.raises(WorkerCrashedError, match="preempting"):
            ray_tpu.get(r, timeout=60)


# ---------------------------------------------------------------------------
# Scenario 5: worker killed mid-async-checkpoint-save -> resume from the
# last COMMITTED step, never a torn one
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 404,
      # Scripted: the first spawned worker's checkpoint writer dies at
      # its 3rd save (ordinal 2) — right after the shard data is on disk
      # but BEFORE the COMMIT rename, leaving checkpoint_000002 torn.
      # Its replacement (a fresh spawn ordinal) saves unharmed.
      "chaos_ckpt_kill_salts": "1",
      "chaos_ckpt_kill_at": 2}],
    indirect=True)
def test_worker_killed_mid_async_save_resumes_from_committed(
        chaos_cluster, tmp_path):
    """ISSUE acceptance criterion: chaos-killing a worker mid-save must
    leave restore_latest() pointing at the previous committed step; the
    elastic restart resumes there and the run still completes."""
    from ray_tpu.air import (
        FailureConfig, RunConfig, ScalingConfig)
    from ray_tpu.checkpoint import is_committed
    from ray_tpu.train import DataParallelTrainer

    def loop(config):
        import numpy as np
        from ray_tpu.train import session

        mgr = session.get_checkpoint_manager()
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.to_dict()["step"]) + 1
        for step in range(start, 6):
            state = {"w": np.full((16,), float(step)), "step": step}
            handle = mgr.save(step, state)
            # Serialize save->report so the scripted kill lands at a
            # deterministic step (the writer is async; without the wait
            # the os._exit could race the next report's RPC).
            handle._event.wait(30)
            session.report({"step": step, "resumed_from": start},
                           checkpoint=handle)

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="chaos_ckpt", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)))
    result = trainer.fit()
    assert result.error is None
    # Steps 0 and 1 committed; the save of step 2 died pre-COMMIT, so the
    # restarted gang resumed from committed step 1 (start == 2) and the
    # run still reached the end.
    assert result.metrics["step"] == 5
    assert result.metrics["resumed_from"] == 2
    resumes = {m["resumed_from"] for m in result.metrics_history}
    assert resumes == {0, 2}
    # Every surviving directory is committed — the torn step-2 dir was
    # either overwritten by the new incarnation or GC'd, never restored.
    root = tmp_path / "chaos_ckpt"
    assert sorted(p.name for p in root.iterdir())[-1] == "checkpoint_000005"
    for p in root.iterdir():
        assert is_committed(str(p)), f"torn directory survived: {p}"
    final = result.checkpoint.to_dict()
    assert final["step"] == 5


# ---------------------------------------------------------------------------
# Scenario 6: serve-plane graceful degradation under chaos
# ---------------------------------------------------------------------------

@pytest.fixture
def serve_chaos_cluster(request):
    """chaos_cluster + the serve control plane, torn down with the
    process-local router states cleared (they cache replica handles
    across cluster generations)."""
    cfg = dict(getattr(request, "param", {}))
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    from ray_tpu import serve
    serve.start()
    try:
        yield info
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        from ray_tpu.serve import _private as sp
        with sp._router_states_lock:
            sp._router_states.clear()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


def _metric(name):
    from ray_tpu.util import metrics
    return metrics.read(name) or 0.0


@pytest.mark.parametrize(
    "serve_chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 31,
      # Scripted: EVERY serve replica process ("*") dies at its 4th
      # serve event — the dispatch is event 0 and each token pull is one
      # event, so the replica is killed mid-generation after streaming 3
      # tokens.  The replacement incarnation re-arms at the same ordinal,
      # so the 8-token request needs exactly two failovers (3 + 3 + 2
      # tokens) — within the serve_failover_attempts default.
      "chaos_kill_replica_salts": "*",
      "chaos_kill_replica_at": 4,
      "chaos_max_faults": 1}],
    indirect=True)
def test_replica_kill_mid_stream_resumes_token_exact(serve_chaos_cluster):
    """ISSUE acceptance criterion: a scripted chaos_kill_replica mid-
    generation is absorbed by the llm_stream_resume failover policy and
    the streamed greedy output is token-exact with an unfaulted run."""
    from ray_tpu import serve
    from ray_tpu.inference import InferenceEngine

    prompt, budget = [1, 2, 3], 8
    # The unfaulted reference: same model family/config/seed as the
    # deployment, built driver-side (deterministic seeded weights).
    expected = InferenceEngine("gpt", "nano", seed=0).generate(
        prompt, budget)

    handle = serve.run(serve.LLMDeployment.options(
        name="llm_chaos").bind(model="gpt", config="nano", max_lanes=4,
                               seed=0))
    before = _metric("serve_stream_failovers")
    got = list(handle.options("generate",
                              failover=serve.llm_stream_resume)
               .stream(prompt, budget))
    assert got == expected
    # The kills actually happened (two failovers absorbed them).
    assert _metric("serve_stream_failovers") - before >= 1


@pytest.mark.parametrize("serve_chaos_cluster", [{}], indirect=True)
def test_drain_on_downscale_zero_dropped(serve_chaos_cluster):
    """ISSUE acceptance criterion: a scripted downscale during a burst
    of in-flight unary requests completes every request — replicas leave
    the routing table immediately but are only killed after draining, so
    zero ActorDiedErrors surface."""
    from ray_tpu import serve
    from ray_tpu.serve._private import CONTROLLER_NAME, SERVE_NAMESPACE

    @serve.deployment(name="drainy", num_replicas=2,
                      max_concurrent_queries=16)
    def slow(x):
        time.sleep(0.25)
        return x * 2

    handle = serve.run(slow.bind())
    results, errors = [], []

    def one(i):
        try:
            results.append(handle.remote(i).result(timeout=60))
        except Exception as e:   # noqa: BLE001 - recorded for the assert
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # burst is in flight on both replicas
    serve.run(slow.options(num_replicas=1).bind())  # scripted downscale
    for t in threads:
        t.join(120)
    assert not errors, f"requests dropped during drain: {errors!r}"
    assert sorted(results) == [2 * i for i in range(12)]
    # The retired replicas really went through DRAINING, not a hard kill.
    controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        stats = ray_tpu.get(controller.drain_stats.remote(), timeout=30)
        if stats["drained_total"] >= 1 and stats["draining"] == 0:
            break
        time.sleep(0.2)
    assert stats["drained_total"] >= 1
    assert stats["deadline_kills"] == 0


@pytest.mark.parametrize("serve_chaos_cluster", [{}], indirect=True)
def test_overload_sheds_and_recovers(serve_chaos_cluster):
    """ISSUE acceptance criterion: overload driving the bounded
    admission queue past its limit sheds with ServeOverloadedError (with
    a retry-after hint) and the deployment serves normally afterwards."""
    from ray_tpu import serve
    from ray_tpu.exceptions import ServeOverloadedError

    @serve.deployment(name="shedder", num_replicas=1,
                      max_concurrent_queries=1, queue_limit=2)
    def slow(x):
        time.sleep(0.5)
        return x + 1

    handle = serve.run(slow.bind())
    assert handle.remote(0).result(timeout=30) == 1  # warm routing table

    before = _metric("serve_requests_shed")
    ok, shed = [], []

    def one(i):
        try:
            ok.append(handle.remote(i).result(timeout=60))
        except ServeOverloadedError as e:
            shed.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    # 1 executing + 2 queued admitted; the rest shed fast with a hint.
    assert ok and shed
    assert all(e.retry_after_s > 0 for e in shed)
    assert _metric("serve_requests_shed") - before >= len(shed)
    # Recovery: the deployment serves normally once the burst passes.
    assert handle.remote(41).result(timeout=30) == 42


# ---------------------------------------------------------------------------
# Scenario 6b: disaggregated prefill/decode under chaos (serve/kv_tier)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "serve_chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 61,
      # Scripted: the PREFILL replica (controller is worker spawn 1,
      # the prefill gang deploys first = worker 2, decode = worker 3)
      # dies at its 0th serve event — the prefill dispatch itself, i.e.
      # mid-KV-handoff.  prefix routing stays OFF so no scrape calls
      # shift the serve-event ordinals.
      "chaos_kill_replica_salts": "2",
      "chaos_kill_replica_at": 0,
      "chaos_max_faults": 1}],
    indirect=True)
def test_prefill_replica_killed_mid_handoff_decode_reprefills(
        serve_chaos_cluster):
    """ISSUE acceptance criterion: killing the prefill replica mid-KV-
    handoff degrades to a decode-side re-prefill — the stream completes
    token-exact with an unfaulted monolithic run, and the lost handoff
    is recorded on the kv event plane."""
    from ray_tpu import serve
    from ray_tpu.inference import InferenceEngine
    from ray_tpu.util import events

    prompt, budget = list(range(1, 21)), 8
    expected = InferenceEngine("gpt", "nano", seed=0).generate(
        prompt, budget)

    # prefill_retry=False: the dying prefill replica must exercise the
    # degradation path (handoff_lost -> decode re-prefill), not a
    # transparent serve-level retry.
    handle = serve.run_disaggregated(
        model="gpt", config="nano", max_lanes=4, seed=0,
        name="llm_disagg_pchaos", prefill_retry=False)
    got = list(handle.stream(prompt, budget))
    assert got == expected
    lost = events.snapshot(plane="kv", kind="handoff_lost")
    assert lost, "prefill kill did not surface as kv/handoff_lost"


@pytest.mark.parametrize(
    "serve_chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 62,
      # Scripted: the DECODE replica (worker spawn 3 — see above) dies
      # at its 4th serve event: dispatch is event 0 and each token pull
      # is one event, so the stream breaks after 3 delivered tokens.
      # The replacement replica has a fresh (unlisted) ordinal.
      "chaos_kill_replica_salts": "3",
      "chaos_kill_replica_at": 4,
      "chaos_max_faults": 1}],
    indirect=True)
def test_decode_replica_killed_mid_stream_heals_through_disagg(
        serve_chaos_cluster):
    """ISSUE acceptance criterion: killing the decode replica mid-stream
    heals through the disaggregated path — llm_stream_resume resubmits
    with the produced suffix (kv_handoff re-imported idempotently on the
    healed replica) and the total stream is token-exact."""
    from ray_tpu import serve
    from ray_tpu.inference import InferenceEngine

    prompt, budget = list(range(1, 21)), 8
    expected = InferenceEngine("gpt", "nano", seed=0).generate(
        prompt, budget)

    handle = serve.run_disaggregated(
        model="gpt", config="nano", max_lanes=4, seed=0,
        name="llm_disagg_dchaos")
    before = _metric("serve_stream_failovers")
    got = list(handle.stream(prompt, budget))
    assert got == expected
    assert _metric("serve_stream_failovers") - before >= 1


# ---------------------------------------------------------------------------
# Scenario 7: preemption notice -> grace-window save -> resume loses at most
# the in-flight step
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 606,
      # Scripted maintenance event: the head hostd receives a preemption
      # NOTICE (not an instant kill) at its 9th heartbeat tick (~4.5s in,
      # while the train loop is mid-run) with a 5s grace window.  The
      # session's preemption hook saves the current step inside the
      # window; the hostd kills the workers when it expires.
      "chaos_preempt_at": 8,
      "chaos_preempt_target": "head",
      "chaos_preempt_grace_s": 5.0}],
    indirect=True)
def test_preemption_grace_save_resumes_with_at_most_one_step_lost(
        chaos_cluster, tmp_path):
    """ISSUE acceptance criterion: a scripted preemption with a 5s grace
    window triggers a proactive checkpoint save; the elastic restart
    resumes from it having lost at most the step that was in flight when
    the notice landed."""
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.checkpoint import is_committed

    def loop(config):
        import numpy as np
        from ray_tpu.train import session

        mgr = session.get_checkpoint_manager()
        holder = {}

        def rescue(remaining_s):
            # Grace-window save: runs at the next step boundary after the
            # notice, racing the remaining grace seconds.
            h = mgr.save(holder["step"], dict(holder["state"]))
            h._event.wait(30)

        session.set_preemption_hook(rescue)
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.to_dict()["step"]) + 1
        for step in range(start, 6):
            holder["step"] = step
            holder["state"] = {"w": np.full((8,), float(step)),
                               "step": step}
            if step == 0:
                # The only PERIODIC save: everything after step 0 is
                # recoverable solely through the grace-window rescue.
                h = mgr.save(step, dict(holder["state"]))
                h._event.wait(30)
            time.sleep(1.2)
            session.report({"step": step, "resumed_from": start})

    from ray_tpu.train import DataParallelTrainer
    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="preempt", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 5
    resumes = sorted({m["resumed_from"] for m in result.metrics_history})
    assert len(resumes) == 2 and resumes[0] == 0
    r2 = resumes[1]
    assert r2 >= 1
    # Exactly the in-flight step is missing from the delivered history:
    # its report() aborted with TrainPreemptedError AFTER the rescue
    # saved its state, so the restart resumed one past it.
    steps = {m["step"] for m in result.metrics_history}
    assert set(range(6)) - steps == {r2 - 1}
    # The step we resumed from exists only because the rescue committed
    # it inside the grace window (periodic saves stopped at step 0).
    assert is_committed(str(tmp_path / "preempt"
                            / f"checkpoint_{r2 - 1:06d}"))
    from ray_tpu.util import metrics
    assert (metrics.read("train_recoveries",
                         {"reason": "preempted"}) or 0) >= 1


# ---------------------------------------------------------------------------
# Scenario 8: scripted stall -> hang watchdog names the laggard rank with
# live stacks
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 52,
      # Scripted straggler: the SECOND spawned worker's 2nd report()
      # stalls (default chaos_stall_s is effectively forever but
      # interruptible), freezing its beacon at step 1 while its healthy
      # peer advances — exactly the asymmetric-hang shape a watchdog
      # must classify.
      "chaos_stall_worker_salts": "2",
      "chaos_stall_at": 1,
      "train_hang_timeout_s": 6.0,
      "train_beacon_poll_s": 1.0}],
    indirect=True)
def test_hang_watchdog_detects_stalled_rank_with_stacks(chaos_cluster):
    """ISSUE acceptance criterion: a scripted stall is detected within
    train_hang_timeout_s and the TrainHungError names the laggard rank
    and carries per-rank thread stacks from the hostd stack-collection
    RPC — instead of the gang blocking forever in a collective."""
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.exceptions import TrainHungError
    from ray_tpu.train import DataParallelTrainer

    def loop(config):
        from ray_tpu.train import session
        for step in range(4):
            time.sleep(0.2)
            session.report({"step": step})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=0)))
    t0 = time.monotonic()
    result = trainer.fit()
    elapsed = time.monotonic() - t0
    err = result.error
    assert isinstance(err, TrainHungError), f"got {err!r}"
    assert err.timeout_s == 6.0
    # Exactly one rank is the straggler; the healthy rank (blocked on the
    # driver, beacon at a HIGHER step) must not be blamed.
    assert len(err.laggard_ranks) == 1
    assert err.beacon_ages, "laggard beacon ages missing"
    # Live stacks collected through hostd CollectStacks: the stalled user
    # thread is parked under session.report.
    assert err.stacks and "thread" in err.stacks
    assert "report" in err.stacks or "wait" in err.stacks
    assert "--- live worker stacks ---" in str(err)
    assert _metric("train_hangs") >= 1
    # Detected via the watchdog, not some multi-minute RPC timeout.
    assert elapsed < 60


# ---------------------------------------------------------------------------
# Scenario 9: node loss -> gang resizes DOWN onto survivors, token-exact
# with a restart-from-checkpoint baseline
# ---------------------------------------------------------------------------

def test_resize_down_on_node_loss_token_exact(tmp_path):
    """ISSUE acceptance criterion: killing one of two single-CPU nodes
    mid-run re-forms the gang at world size 1 on the survivor (instead
    of waiting forever for a replacement) and the final weights are
    token-exact with replaying from the same COMMITTED step — and with
    a clean unfaulted run."""
    import numpy as np

    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import DataParallelTrainer

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    node2 = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.gcs_address, _system_config={
        # Fast descending gang formation: the post-loss full-size attempt
        # gives up in 3s and re-forms on the survivor.
        "train_pg_timeout_s": 3.0,
        "train_elastic_timeout_s": 60.0})
    N = 10

    def loop(config):
        import numpy as np
        from ray_tpu.train import session

        mgr = session.get_checkpoint_manager()
        ctx = session.get_context()
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            state = ckpt.to_dict()
            start = int(state["step"]) + 1
            w = np.asarray(state["w"]).copy()
        else:
            start, w = 0, np.zeros(4)
        for step in range(start, 10):
            w = w + (step + 1)  # rank-independent: exactness is checkable
            h = None
            if ctx.world_rank == 0:
                h = mgr.save(step, {"w": w, "step": step})
                h._event.wait(30)
            time.sleep(0.5)
            session.report({"step": step, "resumed_from": start,
                            "world_size": ctx.world_size}, checkpoint=h)

    root = tmp_path / "resize_down"

    def killer():
        # Kill the second node only once training has demonstrably
        # progressed at world size 2 (step-2 save on shared storage).
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (root / "checkpoint_000002").exists():
                time.sleep(0.3)
                cluster.remove_node(node2)
                return
            time.sleep(0.1)

    try:
        trainer = DataParallelTrainer(
            loop,
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1,
                resources_per_worker={"CPU": 1}),
            run_config=RunConfig(
                name="resize_down", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2)))
        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        result = trainer.fit()
        kt.join(60)
        assert result.error is None
        assert result.metrics["step"] == N - 1
        sizes = {m["world_size"] for m in result.metrics_history}
        assert sizes == {2, 1}, f"gang sizes seen: {sizes}"
        resumes = sorted({m["resumed_from"]
                          for m in result.metrics_history})
        assert len(resumes) == 2 and resumes[0] == 0
        r2 = resumes[1]
        final = np.asarray(result.checkpoint.to_dict()["w"])
        # Token-exact vs the restart-from-checkpoint baseline: replay
        # from the SAME committed step the resized gang resumed from.
        base = Checkpoint.from_sharded_dir(
            str(root / f"checkpoint_{r2 - 1:06d}")).to_dict()
        w_base = np.asarray(base["w"]).copy()
        for s in range(r2, N):
            w_base = w_base + (s + 1)
        np.testing.assert_array_equal(final, w_base)
        # ... which is also exactly the unfaulted full run.
        clean = np.zeros(4)
        for s in range(N):
            clean = clean + (s + 1)
        np.testing.assert_array_equal(final, clean)
        from ray_tpu.util import metrics
        assert (metrics.read("train_recoveries",
                             {"reason": "failure"}) or 0) >= 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


# ---------------------------------------------------------------------------
# Scenario 10: returned capacity -> gang resizes UP at a step boundary
# ---------------------------------------------------------------------------

def test_resize_up_readmits_returned_node(tmp_path):
    """ISSUE acceptance criterion: a gang that started below target size
    (only one single-CPU node available) re-admits a returning node at a
    step boundary — growing to full size mid-run without losing
    committed progress and without burning the failure budget."""
    import numpy as np

    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import DataParallelTrainer

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.gcs_address, _system_config={
        "train_pg_timeout_s": 2.0,
        "train_elastic_timeout_s": 60.0,
        "train_resize_check_interval_s": 0.5})
    N = 10

    def loop(config):
        import numpy as np
        from ray_tpu.train import session

        mgr = session.get_checkpoint_manager()
        ctx = session.get_context()
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            state = ckpt.to_dict()
            start = int(state["step"]) + 1
            w = np.asarray(state["w"]).copy()
        else:
            start, w = 0, np.zeros(4)
        for step in range(start, 10):
            w = w + (step + 1)
            h = None
            if ctx.world_rank == 0:
                h = mgr.save(step, {"w": w, "step": step})
                h._event.wait(30)
            time.sleep(0.4)
            session.report({"step": step, "world_size": ctx.world_size},
                           checkpoint=h)

    root = tmp_path / "resize_up"

    def returner():
        # Add the second node only after the undersized gang has
        # committed progress, so both world sizes provably trained.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (root / "checkpoint_000001").exists():
                time.sleep(0.2)
                cluster.add_node(num_cpus=1)
                return
            time.sleep(0.1)

    try:
        trainer = DataParallelTrainer(
            loop,
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1,
                resources_per_worker={"CPU": 1}),
            run_config=RunConfig(
                name="resize_up", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2)))
        rt = threading.Thread(target=returner, daemon=True)
        rt.start()
        result = trainer.fit()
        rt.join(60)
        assert result.error is None
        assert result.metrics["step"] == N - 1
        sizes = {m["world_size"] for m in result.metrics_history}
        assert sizes == {1, 2}, f"gang sizes seen: {sizes}"
        # Token-exact through the voluntary resize: replayed steps after
        # the committed resume point fold into the same final weights.
        final = np.asarray(result.checkpoint.to_dict()["w"])
        clean = np.zeros(4)
        for s in range(N):
            clean = clean + (s + 1)
        np.testing.assert_array_equal(final, clean)
        from ray_tpu.util import metrics
        assert (metrics.read("train_recoveries",
                             {"reason": "resize_up"}) or 0) >= 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


# ---------------------------------------------------------------------------
# Node-death propagation plumbing (unit level)
# ---------------------------------------------------------------------------

def test_node_dead_rpc_invalidates_locations():
    """The CoreWorker NodeDead handler drops the dead node's object
    locations, clears the node cache, and purges its leases."""
    info = ray_tpu.init(num_cpus=2, object_store_memory=64 << 20)
    try:
        from ray_tpu import api as _api
        cw = _api._worker

        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote(), timeout=60) == 1
        ghost = "deadbeef" * 4
        with cw._obj_lock:
            states = [st for st in cw.objects.values()]
            for st in states:
                st.locations.add(ghost)
        reply = cw.io.run(cw.pool.get(cw.address).call(
            "CoreWorker", "NodeDead",
            {"node_id": ghost, "address": "127.0.0.1:1"}, timeout=10))
        assert reply["ok"]
        with cw._obj_lock:
            assert all(ghost not in st.locations
                       for st in cw.objects.values())
        assert cw._node_cache is None
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v", "-x"]))
