"""Data-parallel learner group tests (VERDICT r2 item 2).

Reference parity: rllib/core/learner/learner_group.py:51 +
torch_learner.py:154 — the reference scales learners as a DDP-wrapped
actor fleet; here the learner is ONE SPMD program over the mesh's data
axis with a pmean on gradients.  The gate: a dp-8 learner must walk the
same parameter trajectory as the single-chip learner on the same batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel.mesh import MeshConfig, create_mesh
from ray_tpu.rllib.learner import JaxLearner, ppo_loss
from ray_tpu.rllib.sample_batch import SampleBatch


def _fake_ppo_batch(n=512, obs_dim=6, num_actions=3, seed=0):
    rng = np.random.default_rng(seed)
    return SampleBatch({
        SampleBatch.OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        SampleBatch.ACTIONS: rng.integers(0, num_actions, size=n)
            .astype(np.int32),
        SampleBatch.ACTION_LOGP: rng.normal(size=n).astype(np.float32)
            * 0.1 - 1.0,
        SampleBatch.ADVANTAGES: rng.normal(size=n).astype(np.float32),
        SampleBatch.VALUE_TARGETS: rng.normal(size=n).astype(np.float32),
    })


def _make_learner(mesh):
    return JaxLearner(
        6, 3, loss_fn=ppo_loss,
        config={"lr": 3e-3, "grad_clip": 0.5, "num_sgd_iter": 4,
                "sgd_minibatch_size": 128, "clip_param": 0.2},
        seed=7, mesh=mesh)


def test_dp8_learner_matches_single_chip():
    """dp8 and dp1 run the SAME global permutation and per-minibatch
    advantage normalization; gradients pmean to the exact global-minibatch
    gradient, so parameters must match to fp-summation-order tolerance."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    batch = _fake_ppo_batch()
    dp8 = _make_learner(create_mesh(MeshConfig(data=8, fsdp=1)))
    dp1 = _make_learner(create_mesh(MeshConfig(data=1, fsdp=1),
                                    devices=devs[:1]))
    m8 = dp8.update(batch)
    m1 = dp1.update(batch)
    for p8, p1 in zip(jax.tree_util.tree_leaves(dp8.get_weights()),
                      jax.tree_util.tree_leaves(dp1.get_weights())):
        np.testing.assert_allclose(p8, p1, rtol=1e-4, atol=1e-5)
    assert abs(m8["total_loss"] - m1["total_loss"]) < 1e-3


def test_dp_learner_rejects_model_axes():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    with pytest.raises(ValueError, match="data-parallel only"):
        _make_learner(create_mesh(MeshConfig(data=4, tensor=2)))


def test_impala_vtrace_learner_dp():
    """dp V-trace learner: fragment columns slice exactly (V-trace is
    per-sequence), so dp-8 matches the single-chip step."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from ray_tpu.rllib.impala import IMPALAConfig, _VTraceLearner

    T, B, obs_dim, acts = 16, 8, 4, 2
    rng = np.random.default_rng(1)
    batch = SampleBatch({
        SampleBatch.OBS: rng.normal(size=(T, B, obs_dim))
            .astype(np.float32),
        SampleBatch.ACTIONS: rng.integers(0, acts, size=(T, B))
            .astype(np.int32),
        SampleBatch.ACTION_LOGP: (rng.normal(size=(T, B)) * 0.1 - 0.7)
            .astype(np.float32),
        SampleBatch.REWARDS: rng.normal(size=(T, B)).astype(np.float32),
        SampleBatch.TERMINATEDS: np.zeros((T, B), bool),
        SampleBatch.TRUNCATEDS: np.zeros((T, B), bool),
        "bootstrap_obs": rng.normal(size=(B, obs_dim)).astype(np.float32),
    })
    cfg = IMPALAConfig()
    single = _VTraceLearner(obs_dim, acts, cfg, (32,), 3)
    dp = _VTraceLearner(obs_dim, acts, cfg, (32,), 3,
                        mesh=create_mesh(MeshConfig(data=8, fsdp=1)))
    ms = single.update(batch)
    md = dp.update(batch)
    for ps, pd in zip(jax.tree_util.tree_leaves(single.get_weights()),
                      jax.tree_util.tree_leaves(dp.get_weights())):
        np.testing.assert_allclose(ps, pd, rtol=1e-4, atol=1e-5)
    assert abs(ms["total_loss"] - md["total_loss"]) < 1e-3
