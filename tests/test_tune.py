"""Tune tests: grid/random search, ASHA early stopping, PBT exploit/explore,
trainer integration, failure handling.

Reference coverage model: python/ray/tune/tests/ (test_tune_*.py,
test_trial_scheduler*.py) over a real single-node cluster.
"""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import Checkpoint, RunConfig, FailureConfig


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_grid_and_random_search(cluster):
    def objective(config):
        tune.report({"score": config["a"] * 10 + config["b"]})

    grid = tune.Tuner(
        objective,
        param_space={"a": tune.grid_search([1, 2, 3]),
                     "b": tune.uniform(0, 1)},
        tune_config=tune.TuneConfig(metric="score", mode="max", seed=7),
        resources_per_trial={"CPU": 1},
    ).fit()
    assert len(grid) == 3
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["config"]["a"] == 3
    assert 30 <= best.metrics["score"] <= 31


def test_num_samples_random(cluster):
    def objective(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    results = tune.run(objective, config={"x": tune.uniform(0, 1)},
                       num_samples=6, metric="loss", mode="min",
                       resources_per_trial={"CPU": 1})
    assert len(results) == 6
    best = results.get_best_result()
    # get_best_result must return THE argmin trial (exercises mode="min").
    all_losses = [r.metrics["loss"] for r in results if r.metrics]
    assert best.metrics["loss"] == min(all_losses)
    worst_x = max(results, key=lambda r: r.metrics["loss"]).metrics
    assert abs(best.metrics["config"]["x"] - 0.5) <= abs(
        worst_x["config"]["x"] - 0.5)


def test_asha_stops_bad_trials_early(cluster):
    def objective(config):
        for step in range(20):
            # Bad configs plateau high; good ones descend.
            loss = config["lr"] * (20 - step if config["lr"] < 0.5 else 20)
            tune.report({"loss": loss})

    scheduler = tune.ASHAScheduler(metric="loss", mode="min", max_t=20,
                                   grace_period=2, reduction_factor=2)
    results = tune.run(
        objective, config={"lr": tune.grid_search([0.1, 0.2, 0.9, 1.0])},
        scheduler=scheduler, metric="loss", mode="min",
        resources_per_trial={"CPU": 1})
    assert len(results) == 4
    iters = {r.metrics["config"]["lr"]: len(r.metrics_history)
             for r in results if r.metrics}
    # The bad (plateauing) configs must have been cut before 20 iterations.
    assert iters[1.0] < 20 or iters[0.9] < 20
    # At least one good config ran to completion.
    assert max(len(r.metrics_history) for r in results) == 20


def test_trial_error_isolated(cluster):
    def objective(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"ok": 1})

    results = tune.run(objective, config={"x": tune.grid_search([0, 1, 2])},
                       resources_per_trial={"CPU": 1})
    assert len(results) == 3
    assert len(results.errors) == 1
    assert sum(1 for r in results if r.error is None) == 2


def test_pbt_exploit_explore(cluster):
    def objective(config):
        from ray_tpu.tune import get_checkpoint
        start, inherited = 0, config["lr"]
        ckpt = get_checkpoint()
        if ckpt is not None:
            d = ckpt.to_dict()
            start = d["step"] + 1
        for step in range(start, 12):
            # High lr -> good score; PBT should migrate low-lr trials up.
            tune.report({"score": config["lr"] * (step + 1)},
                        checkpoint=Checkpoint.from_dict(
                            {"step": step, "lr": config["lr"]}))

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.5, 1.0, 2.0]}, seed=3,
        quantile_fraction=0.34)
    results = tune.run(
        objective, config={"lr": tune.grid_search([0.1, 1.0, 2.0])},
        scheduler=pbt, metric="score", mode="max",
        resources_per_trial={"CPU": 1})
    assert len(results) == 3
    assert not results.errors
    # The originally-worst trial should have been perturbed off lr=0.1.
    final_lrs = [r.metrics["config"]["lr"] for r in results if r.metrics]
    assert any(lr != 0.1 for lr in final_lrs)
    best = results.get_best_result()
    assert best.metrics["score"] >= 12  # lr >= 1.0 for 12 steps


def test_tuner_over_trainer(cluster):
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import DataParallelTrainer

    def loop(config):
        from ray_tpu.train import session
        session.report({"final": config["scale"] * 2})

    trainer = DataParallelTrainer(
        loop, train_loop_config={"scale": 0},
        scaling_config=ScalingConfig(num_workers=1))
    results = tune.Tuner(
        trainer,
        param_space={"scale": tune.grid_search([1, 5])},
        tune_config=tune.TuneConfig(metric="final", mode="max"),
    ).fit()
    assert len(results) == 2
    assert results.get_best_result().metrics["final"] == 10


def test_tuner_experiment_resume(cluster, tmp_path):
    """Tuner.restore: finished trials keep results; unfinished trials
    restart from their latest checkpoint (reference:
    tune/execution/experiment_state.py + Tuner.restore)."""

    def objective(config):
        import time as _t

        from ray_tpu.train import session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["step"] if ckpt else 0
        for step in range(start, 4):
            if config["crash"] and step == 2 and start == 0:
                raise RuntimeError("simulated preemption")
            tune.report({"score": config["x"] * 10 + step},
                        checkpoint=Checkpoint.from_dict({"step": step + 1}))
            _t.sleep(0.05)

    exp = str(tmp_path / "exp")
    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2]),
                     "crash": tune.grid_search([True])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="resume-exp", storage_path=exp),
        resources_per_trial={"CPU": 1},
    )
    first = tuner.fit()
    # Both trials crashed at step 2 (max_failures=0 -> ERROR), but their
    # step-2 checkpoints + partial results are in the experiment state.
    assert len(first.errors) == 2

    restored = tune.Tuner.restore(f"{exp}/resume-exp", objective)
    second = restored.fit()
    assert not second.errors
    # Resumed from checkpoint: start==2 skips the crash branch and each
    # trial finishes through step 3.
    best = second.get_best_result()
    assert best.metrics["score"] == 23  # x=2, step=3
    for r in second:
        assert r.metrics["score"] % 10 == 3


def test_tpe_beats_random_on_2d_objective():
    """VERDICT r2 item 6 gate: the native model-based searcher must beat
    random search on a deterministic 2-d objective within a fixed trial
    budget (reference role: tune/search/optuna_search.py)."""
    from ray_tpu.tune.search import BasicVariantGenerator, TPESearcher
    from ray_tpu.tune import search as s

    def objective(cfg):
        return (cfg["x"] - 0.23) ** 2 + (cfg["y"] + 0.51) ** 2

    space = {"x": s.uniform(-2.0, 2.0), "y": s.uniform(-2.0, 2.0)}
    budget = 60

    def run_searcher(searcher):
        best = float("inf")
        for i in range(budget):
            tid = f"t{i}"
            cfg = searcher.suggest(tid)
            val = objective(cfg)
            searcher.on_trial_complete(tid, {"loss": val})
            best = min(best, val)
        return best

    tpe_best = run_searcher(TPESearcher(space, metric="loss", mode="min",
                                        n_startup=10, seed=42))
    rnd_best = run_searcher(
        BasicVariantGenerator(space, num_samples=budget, seed=42))
    assert tpe_best < rnd_best, (tpe_best, rnd_best)
    assert tpe_best < 0.05  # converged near the optimum


def test_tpe_categorical_and_log_dims():
    from ray_tpu.tune import search as s
    from ray_tpu.tune.search import TPESearcher

    def objective(cfg):
        base = 0.0 if cfg["act"] == "gelu" else 1.0
        import math
        return base + abs(math.log10(cfg["lr"]) + 3.0)  # best at 1e-3

    space = {"lr": s.loguniform(1e-5, 1e-1),
             "act": s.choice(["relu", "gelu", "tanh"])}
    searcher = TPESearcher(space, metric="loss", n_startup=8, seed=3)
    best_cfg, best = None, float("inf")
    for i in range(50):
        cfg = searcher.suggest(f"t{i}")
        val = objective(cfg)
        searcher.on_trial_complete(f"t{i}", {"loss": val})
        if val < best:
            best, best_cfg = val, cfg
    assert best_cfg["act"] == "gelu"
    assert 1e-4 < best_cfg["lr"] < 1e-2


def test_hyperband_scheduler_stops_bad_trials():
    from ray_tpu.tune.schedulers import CONTINUE, STOP, HyperBandScheduler

    class _T:
        def __init__(self, tid):
            self.trial_id = tid
            self.reached_rungs = set()

    hb = HyperBandScheduler(metric="loss", mode="min", max_t=27,
                            reduction_factor=3)
    assert len(hb.brackets) == hb.s_max + 1
    # Feed one bracket: trials from the SAME bracket compete at rungs.
    trials = [_T(f"x{i}") for i in range(len(hb.brackets) * 3)]
    decisions = {}
    for t in range(1, 28):
        for i, tr in enumerate(trials):
            if decisions.get(tr.trial_id) == STOP:
                continue
            # Trial i's loss is proportional to i: later trials worse.
            d = hb.on_trial_result(tr, {"training_iteration": t,
                                        "loss": float(i)})
            decisions[tr.trial_id] = d
    stopped = [tid for tid, d in decisions.items() if d == STOP]
    assert stopped  # bad trials got cut before max_t
    # The best trial of bracket 0 survived to max_t.
    assert decisions[trials[0].trial_id] == STOP  # via t >= max_t


def test_bohb_searcher_with_hyperband(cluster):
    """BOHB = HyperBand budgets + TPE conditioned per budget (reference:
    tune/search/bohb + schedulers/hb_bohb.py roles): on a deterministic
    objective it must beat random search under the same trial budget."""
    from ray_tpu import tune
    from ray_tpu.tune.search import BasicVariantGenerator, BOHBSearcher

    space = {"x": tune.uniform(-4.0, 4.0), "y": tune.uniform(-4.0, 4.0)}

    def objective(config):
        # Iterative so HyperBand has rungs to cut on.
        for i in range(9):
            loss = (config["x"] - 1.2) ** 2 + (config["y"] + 0.7) ** 2
            tune.report({"loss": loss})

    def best_with(searcher, scheduler=None):
        tuner = tune.Tuner(
            objective,
            tune_config=tune.TuneConfig(
                metric="loss", mode="min", num_samples=40,
                search_alg=searcher, scheduler=scheduler,
                max_concurrent_trials=4),
        )
        grid = tuner.fit()
        return grid.get_best_result(metric="loss", mode="min") \
            .metrics["loss"]

    bohb = best_with(
        BOHBSearcher(space, metric="loss", mode="min", n_startup=6,
                     seed=5),
        tune.HyperBandScheduler(metric="loss", mode="min", max_t=9,
                                reduction_factor=3))
    rnd = best_with(BasicVariantGenerator(space, num_samples=40, seed=5))
    # The model must find a clearly better optimum than random under the
    # same budget (deterministic objective, fixed seeds).
    assert bohb <= rnd * 1.05, (bohb, rnd)
    assert bohb < 1.0, bohb


def test_pb2_gp_explore_targets_good_region():
    """PB2's GP-UCB explore must learn from observed (hparam, reward-delta)
    data: with history showing lr near 0.9 yields high deltas and lr near
    0.1 yields low ones, the suggested config lands in the good half."""
    from ray_tpu.tune.schedulers import PB2

    class _T:
        def __init__(self, tid, lr):
            self.trial_id = tid
            self.config = {"lr": lr}
            self.reached_rungs = set()
            self.exploit_from = None
            self.explored_config = None
            self.checkpoint = None  # no donor ckpt: no exploits, pure GP data

    pb2 = PB2(metric="score", mode="max", perturbation_interval=2,
              hyperparam_bounds={"lr": [0.0, 1.0]}, seed=11)
    # Feed windows: reward delta == lr (monotone), several trials/windows.
    trials = [_T(f"t{i}", 0.1 + 0.2 * i) for i in range(5)]
    score = {t.trial_id: 0.0 for t in trials}
    for step in (2, 4, 6):
        for t in trials:
            score[t.trial_id] += t.config["lr"]
            pb2.on_trial_result(t, {"training_iteration": step,
                                    "score": score[t.trial_id]})
    assert len(pb2._data) >= 10  # windows recorded after the first boundary
    suggestions = [pb2._explore({"lr": 0.1})["lr"] for _ in range(5)]
    assert all(0.0 <= s <= 1.0 for s in suggestions)
    # GP-UCB should concentrate suggestions in the high-delta region.
    assert sum(s > 0.5 for s in suggestions) >= 4, suggestions


def test_pb2_end_to_end_migrates_bad_trials(cluster):
    def objective(config):
        import time as _time

        from ray_tpu.tune import get_checkpoint
        start = 0
        ckpt = get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 16):
            # Pace the loop so concurrently-launched trials OVERLAP in
            # wall time even when worker spawns stagger under CI load:
            # exploitation only happens at a perturbation boundary where
            # the scheduler has windows from the trial's peers, so a bad
            # trial that sprints through every step before its peers
            # report anything never migrates — the load-timing flake
            # this pacing (plus the extra boundaries of 16 steps over
            # 12) retires.
            _time.sleep(0.05)
            tune.report({"score": config["lr"] * (step + 1)},
                        checkpoint=Checkpoint.from_dict({"step": step}))

    pb2 = tune.PB2(metric="score", mode="max", perturbation_interval=3,
                   hyperparam_bounds={"lr": [0.1, 2.0]}, seed=3,
                   quantile_fraction=0.34)
    results = tune.run(
        objective, config={"lr": tune.grid_search([0.1, 1.0, 2.0])},
        scheduler=pb2, metric="score", mode="max",
        resources_per_trial={"CPU": 1})
    assert len(results) == 3
    assert not results.errors
    final_lrs = [r.metrics["config"]["lr"] for r in results if r.metrics]
    assert any(lr != 0.1 for lr in final_lrs)  # worst trial was moved


def test_resource_changing_scheduler_grows_allocation(cluster):
    """With 8 cluster CPUs and 2 trials at base CPU:1, DistributeResources
    should grow each live trial to CPU:4 at the interval boundary and the
    controller must restart it from checkpoint under the new allocation."""
    from ray_tpu.tune.controller import TuneController
    from ray_tpu.tune.schedulers import ResourceChangingScheduler
    from ray_tpu.tune.search import BasicVariantGenerator

    def objective(config):
        from ray_tpu.tune import get_checkpoint
        start = 0
        ckpt = get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 6):
            tune.report({"score": float(step)},
                        checkpoint=Checkpoint.from_dict({"step": step}))

    sched = ResourceChangingScheduler(resource_interval=2)
    sched.set_search_properties("score", "max")
    searcher = BasicVariantGenerator({"x": tune.uniform(0, 1)},
                                     num_samples=2, seed=0)
    ctl = TuneController(objective, searcher=searcher, scheduler=sched,
                         max_concurrent=2, resources_per_trial={"CPU": 1})
    ctl.run(deadline_s=120)
    assert all(t.state == "TERMINATED" for t in ctl.trials)
    # Both trials ran to completion (checkpoint resume across the restart)
    assert all(t.last_result["score"] == 5.0 for t in ctl.trials)
    # Each trial grew past its base CPU:1 (to 4 while both live; a trial
    # reallocating after its peer terminates may claim the freed capacity).
    grown = [t for t in ctl.trials if (t.resources or {}).get("CPU", 1) >= 4]
    assert len(grown) == 2, [t.resources for t in ctl.trials]


def test_pb2_window_resets_on_exploit():
    """The score jump from adopting a donor checkpoint must not be
    recorded as a reward delta for the explored config."""
    from ray_tpu.tune.schedulers import PB2

    class _T:
        def __init__(self, tid, lr):
            self.trial_id = tid
            self.config = {"lr": lr}
            self.reached_rungs = set()
            self.exploit_from = None
            self.explored_config = None
            self.checkpoint = object()

    pb2 = PB2(metric="score", mode="max", perturbation_interval=2,
              hyperparam_bounds={"lr": [0.0, 1.0]}, seed=1,
              quantile_fraction=0.5)
    good, bad = _T("good", 0.9), _T("bad", 0.1)
    pb2.on_trial_result(good, {"training_iteration": 2, "score": 10.0})
    d = pb2.on_trial_result(bad, {"training_iteration": 2, "score": 1.0})
    # Exploit decided at the first boundary (both trials known).
    assert d == "STOP" and bad.explored_config is not None
    assert "bad" not in pb2._window_start  # window dropped on exploit
    # Post-restart: controller clears the decision and the trial resumes
    # from the DONOR's checkpoint at donor-level scores.
    bad.config = bad.explored_config
    bad.explored_config = None
    n_obs = len(pb2._data)
    pb2.on_trial_result(bad, {"training_iteration": 4, "score": 11.0})
    # The 1.0 -> 11.0 checkpoint jump was NOT recorded as a delta; the
    # boundary only restarts the window.
    assert len(pb2._data) == n_obs
    assert pb2._window_start["bad"] == 11.0
    # The window AFTER the restart does record (a genuine config effect).
    pb2.on_trial_result(bad, {"training_iteration": 6, "score": 12.5})
    assert len(pb2._data) == n_obs + 1
    assert abs(pb2._data[-1][2] - 1.5) < 1e-9
