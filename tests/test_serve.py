"""Serve tests (reference coverage model: python/ray/serve/tests/) against
a real cluster: deployments, scaling, composition, HTTP ingress, batching,
replica failure healing."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=64 << 20)
    serve.start()
    yield info
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment(cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert handle.remote("hi").result(timeout=60) == {"echo": "hi"}


def test_class_deployment_with_state(cluster):
    @serve.deployment(name="counter")
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self, inc):
            self.n += inc
            return self.n

        def peek(self):
            return self.n

    handle = serve.run(Counter.bind(100))
    assert handle.remote(5).result(timeout=60) == 105
    assert handle.peek.remote().result(timeout=60) == 105
    serve.delete("counter")


def test_multiple_replicas_round_robin(cluster):
    @serve.deployment(name="pidsvc", num_replicas=2)
    class PidSvc:
        def __call__(self, _):
            import os
            return os.getpid()

    handle = serve.run(PidSvc.bind())
    pids = {handle.remote(None).result(timeout=60) for _ in range(8)}
    assert len(pids) == 2
    serve.delete("pidsvc")


def test_deployment_graph_composition(cluster):
    @serve.deployment(name="preprocess")
    def preprocess(x):
        return x * 2

    @serve.deployment(name="model")
    class Model:
        def __init__(self, downstream):
            self.downstream = downstream

        def __call__(self, x):
            doubled = self.downstream.remote(x).result(timeout=30)
            return doubled + 1

    handle = serve.run(Model.bind(preprocess.bind()))
    assert handle.remote(10).result(timeout=60) == 21
    serve.delete("model")
    serve.delete("preprocess")


def test_http_ingress(cluster):
    import json
    import urllib.request

    @serve.deployment(name="httpsvc")
    def svc(payload):
        return {"doubled": payload["x"] * 2}

    serve.run(svc.bind())
    port = serve.start(with_proxy=True)
    assert port

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/httpsvc",
        data=json.dumps({"x": 21}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"result": {"doubled": 42}}

    # Unknown deployment -> 404.
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/nosuch",
        data=json.dumps({}).encode())
    try:
        urllib.request.urlopen(req, timeout=30)
        raised = False
    except urllib.error.HTTPError as e:
        raised = e.code == 404
    assert raised
    serve.delete("httpsvc")


def test_batching(cluster):
    @serve.deployment(name="batcher")
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def __call__(self, x):
            return self.handle(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(
        Batcher.options(max_concurrent_queries=16).bind())
    refs = [handle.remote(i) for i in range(8)]
    results = sorted(r.result(timeout=60) for r in refs)
    assert results == [0, 10, 20, 30, 40, 50, 60, 70]
    sizes = handle.sizes.remote().result(timeout=60)
    assert max(sizes) > 1  # batching actually combined requests
    serve.delete("batcher")


def test_replica_failure_heals(cluster):
    @serve.deployment(name="fragile", num_replicas=1)
    class Fragile:
        def __call__(self, cmd):
            if cmd == "die":
                import os
                os._exit(1)
            return "alive"

    handle = serve.run(Fragile.bind())
    assert handle.remote("ping").result(timeout=60) == "alive"
    try:
        handle.remote("die").result(timeout=60)
    except Exception:
        pass
    # Controller heals the replica set; next call must succeed.
    deadline = time.monotonic() + 60
    ok = False
    while time.monotonic() < deadline:
        try:
            if handle.remote("ping").result(timeout=30) == "alive":
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok
    serve.delete("fragile")


def test_status_and_scaling(cluster):
    @serve.deployment(name="scaleme", num_replicas=1)
    def f(x):
        return x

    serve.run(f.bind())
    assert serve.status()["scaleme"]["num_replicas"] == 1
    serve.run(f.options(num_replicas=3).bind())
    assert serve.status()["scaleme"]["num_replicas"] == 3
    serve.delete("scaleme")


def test_autoscaling_grows_and_shrinks(cluster):
    """Queue-depth autoscaling: replicas grow under sustained load and
    shrink back when idle (reference: _private/autoscaling_policy.py)."""
    import threading

    @serve.deployment(name="auto", max_concurrent_queries=4,
                      autoscaling_config={"min_replicas": 1,
                                          "max_replicas": 3,
                                          "target_ongoing_requests": 1.0,
                                          "upscale_delay_s": 0.1,
                                          "downscale_delay_s": 0.5})
    def slow(x):
        time.sleep(0.4)
        return x

    handle = serve.run(slow.bind())
    assert handle.remote(0).result(timeout=60) == 0
    assert serve.status()["auto"]["num_replicas"] == 1

    # Sustained load: concurrent callers long enough for the control
    # loop to react even on a loaded 1-core CI host.
    stop = time.monotonic() + 15
    errors = []

    def worker():
        while time.monotonic() < stop:
            try:
                handle.remote(1).result(timeout=60)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=worker) for _ in range(12)]
    for t in threads:
        t.start()
    grew = False
    while time.monotonic() < stop:
        if serve.status()["auto"]["num_replicas"] > 1:
            grew = True
            break
        time.sleep(0.2)
    stop = time.monotonic()  # release workers once growth is observed
    for t in threads:
        t.join()
    assert not errors, errors[:1]
    assert grew, "autoscaler never scaled up under load"

    # Idle: must shrink back to min_replicas.
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        if serve.status()["auto"]["num_replicas"] == 1:
            break
        time.sleep(0.3)
    assert serve.status()["auto"]["num_replicas"] == 1
    serve.delete("auto")


def test_long_poll_config_propagation(cluster):
    """A live handle learns about re-deployments via the controller
    long-poll, without forced refreshes (reference: long_poll.py:68)."""
    @serve.deployment(name="lp")
    def v1(x):
        return "v1"

    handle = serve.run(v1.bind())
    assert handle.remote(0).result(timeout=60) == "v1"

    @serve.deployment(name="lp")
    def v2(x):
        return "v2"

    serve.run(v2.bind())
    deadline = time.monotonic() + 15
    seen = None
    while time.monotonic() < deadline:
        seen = handle.remote(0).result(timeout=60)
        if seen == "v2":
            break
        time.sleep(0.2)
    assert seen == "v2", "handle never picked up the new version"
    serve.delete("lp")


def test_http_proxy_concurrency(cluster):
    """30 parallel slow HTTP requests overlap on the async proxy instead
    of serializing through a thread pool."""
    import concurrent.futures
    import json as jsonlib
    import urllib.request

    @serve.deployment(name="slowhttp", num_replicas=2,
                      max_concurrent_queries=32)
    def slowhttp(x):
        time.sleep(0.3)
        return x

    serve.run(slowhttp.bind())
    port = serve.start(with_proxy=True)

    def one(i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/slowhttp",
            data=jsonlib.dumps(i).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return jsonlib.loads(resp.read())["result"]

    t0 = time.monotonic()
    with concurrent.futures.ThreadPoolExecutor(max_workers=30) as pool:
        results = list(pool.map(one, range(30)))
    elapsed = time.monotonic() - t0
    assert sorted(results) == list(range(30))
    # Serial execution would be >= 30 * 0.3 = 9s; two replicas x overlap
    # must land far below that.
    assert elapsed < 6.0, f"requests serialized: {elapsed:.1f}s"
    serve.delete("slowhttp")


def test_serve_cli_deploy_from_config(tmp_path, monkeypatch):
    """`serve deploy <config>` imports an application, applies per-
    deployment overrides, and reports status (reference: serve CLI +
    schema.py config deploy)."""
    import io
    import json
    import subprocess
    import sys
    from contextlib import redirect_stdout

    from ray_tpu.cluster_utils import Cluster

    app_mod = tmp_path / "my_serve_app.py"
    app_mod.write_text(
        "import ray_tpu\n"
        "from ray_tpu import serve\n\n"
        "@serve.deployment(name='hello')\n"
        "def hello(x):\n"
        "    return {'hi': x}\n\n"
        "app = hello.bind()\n")
    config = tmp_path / "serve_config.json"
    config.write_text(json.dumps({
        "applications": [{
            "import_path": "my_serve_app:app",
            "deployments": [{"name": "hello", "num_replicas": 2}],
        }]}))

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4})
    try:
        import os
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", "serve",
             "deploy", str(config), "--address", cluster.address],
            capture_output=True, text=True, timeout=180,
            cwd=str(tmp_path), env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert '"hello"' in proc.stdout
        assert '"num_replicas": 2' in proc.stdout
    finally:
        cluster.shutdown()


def test_usage_stats_written(tmp_path):
    from ray_tpu._private import usage

    stats = usage.collect_usage({"probe": 1})
    assert stats["probe"] == 1 and "ray_tpu_version" in stats
    path = usage.record_usage(str(tmp_path))
    assert path and tmp_path.joinpath("usage_stats.json").exists()


def test_async_deployment_intra_replica_concurrency(cluster):
    """A single replica hosting an async handler must overlap awaits on
    its persistent event loop (reference: replica.py:268 runs a user
    event loop): 10 concurrent 150ms-await requests complete together in
    ~1 await's time, not ~10x serially (VERDICT r2 item 10)."""

    @serve.deployment(name="aio", num_replicas=1)
    class Slow:
        async def __call__(self, _):
            import asyncio
            await asyncio.sleep(0.15)
            import os
            return os.getpid()

    handle = serve.run(Slow.bind())
    handle.remote(None).result(timeout=60)  # warm the path
    t0 = time.monotonic()
    futs = [handle.remote(None) for _ in range(10)]
    pids = {f.result(timeout=60) for f in futs}
    dt = time.monotonic() - t0
    assert len(pids) == 1, "expected exactly one replica"
    # Serial execution would take >= 1.5s; overlapped ~0.15s. The bound
    # leaves slack for a loaded single-core CI host.
    assert dt < 0.9, f"async requests did not overlap: {dt:.2f}s"
    serve.delete("aio")


# ---------------------------------------------------------------------------
# ASGI ingress + streaming (reference: serve/api.py @serve.ingress +
# http_proxy.py's ASGI host; streaming DeploymentResponseGenerator).
# ---------------------------------------------------------------------------


def _tiny_asgi_app():
    """Dependency-free ASGI app with two routes, path/query passthrough
    and a chunked streaming route."""

    async def app(scope, receive, send):
        assert scope["type"] == "http"
        path = scope["path"]
        if path == "/hello":
            body = b"hi " + scope["query_string"]
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type", b"text/plain"),
                                    (b"x-route", b"hello")]})
            await send({"type": "http.response.body", "body": body})
        elif path.startswith("/echo/"):
            msg = await receive()
            body = path.split("/echo/", 1)[1].encode() + b":" + \
                msg.get("body", b"")
            await send({"type": "http.response.start", "status": 201,
                        "headers": [(b"content-type", b"text/plain")]})
            await send({"type": "http.response.body", "body": body})
        elif path == "/stream":
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type", b"text/plain")]})
            for i in range(5):
                await send({"type": "http.response.body",
                            "body": f"c{i};".encode(), "more_body": True})
            await send({"type": "http.response.body", "body": b"end"})
        else:
            await send({"type": "http.response.start", "status": 404,
                        "headers": []})
            await send({"type": "http.response.body", "body": b"nope"})

    return app


def test_asgi_ingress_routes_and_streaming(cluster):
    """An ASGI app mounted on ONE deployment serves multiple routes with
    path/query/body passthrough through the HTTP proxy, and a chunked
    response streams through end to end."""
    import urllib.request

    @serve.deployment(name="asgiapp")
    @serve.ingress(_tiny_asgi_app())
    class Api:
        pass

    serve.run(Api.bind())
    port = serve.start(with_proxy=True)
    base = f"http://127.0.0.1:{port}/asgiapp"

    with urllib.request.urlopen(base + "/hello?who=tpu", timeout=30) as r:
        assert r.status == 200
        assert r.headers["x-route"] == "hello"
        assert r.read() == b"hi who=tpu"

    req = urllib.request.Request(base + "/echo/abc", data=b"payload",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 201
        assert r.read() == b"abc:payload"

    with urllib.request.urlopen(base + "/stream", timeout=30) as r:
        assert r.read() == b"c0;c1;c2;c3;c4;end"

    import urllib.error
    try:
        urllib.request.urlopen(base + "/missing", timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    serve.delete("asgiapp")


def test_handle_streaming_is_incremental(cluster):
    """handle.stream() pulls generator chunks one at a time from the
    replica: the consumer sees chunk k BEFORE the producer has emitted
    chunk k+1 (pull-based, not collect-then-return)."""

    @serve.deployment(name="streamer")
    class Streamer:
        async def tokens(self, n):
            for i in range(n):
                await __import__("asyncio").sleep(0.15)
                yield {"token": i, "emitted_at": time.monotonic()}

    serve.run(Streamer.bind())
    h = serve.get_deployment_handle("streamer").options("tokens")
    arrivals = []
    chunks = []
    for chunk in h.stream(4):
        arrivals.append(time.monotonic())
        chunks.append(chunk["token"])
    assert chunks == [0, 1, 2, 3]
    # Incremental: successive arrivals are separated by the producer's
    # sleep — a collect-then-return stream would arrive all at once.
    gaps = [b - a for a, b in zip(arrivals, arrivals[1:])]
    assert all(g > 0.05 for g in gaps), gaps
    serve.delete("streamer")


def test_generator_method_non_stream_call_raises_cleanly(cluster):
    """A generator method called through the NON-streaming path
    (handle.remote(), plain HTTP dispatch) raises a clear TypeError
    directing the caller to the streaming API — and must not leak the
    replica's in-flight stream slot (reference: streaming methods
    require the streaming handle API)."""

    @serve.deployment(name="genmat", max_concurrent_queries=2)
    class GenMat:
        def chunks(self, n):
            for i in range(n):
                yield i

        def plain(self):
            return "ok"

    serve.run(GenMat.bind())
    h = serve.get_deployment_handle("genmat")
    # Repeat PAST max_concurrent_queries: a leaked slot per call would
    # saturate the replica and time out the later calls.
    for _ in range(5):
        with pytest.raises(Exception, match="stream"):
            h.options("chunks").remote(3).result(timeout=30)
    # The replica still serves normal calls (no slots were leaked) and
    # the streaming API still works.
    assert h.options("plain").remote().result(timeout=30) == "ok"
    assert list(h.options("chunks").stream(3)) == [0, 1, 2]
    serve.delete("genmat")


def test_asgi_receive_does_not_fabricate_disconnect(cluster):
    """Frameworks (Starlette listen_for_disconnect) await receive()
    concurrently while streaming; a fabricated http.disconnect would
    cancel the stream immediately.  The shim must block instead."""
    import asyncio
    import urllib.request

    async def app(scope, receive, send):
        await receive()  # request body
        cancelled = asyncio.Event()

        async def watch_disconnect():
            msg = await receive()   # must BLOCK, not return immediately
            if msg["type"] == "http.disconnect":
                cancelled.set()

        watcher = asyncio.ensure_future(watch_disconnect())
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"text/plain")]})
        for i in range(3):
            await asyncio.sleep(0.05)
            if cancelled.is_set():   # the bug: fires on fabricated msg
                break
            await send({"type": "http.response.body",
                        "body": f"c{i};".encode(), "more_body": True})
        await send({"type": "http.response.body", "body": b"",
                    "more_body": False})
        watcher.cancel()

    @serve.deployment(name="sseapp")
    @serve.ingress(app)
    class SSE:
        pass

    serve.run(SSE.bind())
    port = serve.start(with_proxy=True)
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/sseapp/x", timeout=30).read()
    assert body == b"c0;c1;c2;", body
    serve.delete("sseapp")


# ---------------------------------------------------------------------------
# Graceful degradation: deadlines, draining, failover, shedding plumbing
# ---------------------------------------------------------------------------

def test_request_deadline_bounds_admission_wait(cluster):
    """A handle timeout_s caps how long a request may wait for a replica
    slot: with the only slot busy, the second request times out at its
    deadline instead of sitting in the admission queue for the full
    backpressure window."""
    import threading

    @serve.deployment(name="deadliner", num_replicas=1,
                      max_concurrent_queries=1)
    def slow(x):
        time.sleep(1.5)
        return x

    handle = serve.run(slow.bind())
    handle.remote("warm").result(timeout=60)  # routing table populated

    t = threading.Thread(
        target=lambda: handle.remote("hog").result(timeout=60))
    t.start()
    time.sleep(0.2)  # the hog owns the only slot
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        handle.options(timeout_s=0.3).remote("late").result(timeout=60)
    assert time.monotonic() - t0 < 1.2
    t.join(60)
    serve.delete("deadliner")


def test_deadline_propagates_to_replica(cluster):
    """A deadline-aware deployment (signature takes `_deadline_s`)
    receives the remaining budget server-side."""
    @serve.deployment(name="dlaware")
    def report(x, _deadline_s=None):
        return _deadline_s

    handle = serve.run(report.bind())
    # No deadline configured: nothing injected.
    assert handle.remote(0).result(timeout=60) is None
    got = handle.options(timeout_s=7.5).remote(0).result(timeout=60)
    assert got is not None and 0 < got <= 7.5
    serve.delete("dlaware")


def test_stream_deadline_aborts_mid_stream(cluster):
    """A stream that outlives its request deadline is aborted — client
    raises, and the replica-side generator is closed (its finally runs)
    instead of producing for nobody."""
    from ray_tpu.exceptions import TaskError

    @serve.deployment(name="slowstream", num_replicas=1)
    def ticks(n):
        for i in range(n):
            time.sleep(0.25)
            yield i

    handle = serve.run(ticks.bind())
    got = []
    with pytest.raises((TimeoutError, TaskError)):
        for c in handle.options(timeout_s=0.6).stream(100):
            got.append(c)
    assert len(got) < 100
    serve.delete("slowstream")


def test_stream_failover_replay_skips_delivered_chunks(cluster):
    """Generic mid-stream failover: kill the replica mid-stream; with
    failover="replay" the handle heals, resubmits, skips the chunks the
    consumer already saw, and the stream completes without duplicates."""
    from ray_tpu.serve._private import (
        CONTROLLER_NAME, SERVE_NAMESPACE)

    @serve.deployment(name="replaysrc", num_replicas=1)
    def count(n):
        for i in range(n):
            time.sleep(0.05)
            yield i

    handle = serve.run(count.bind())
    controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    got = []
    for c in handle.options(failover="replay").stream(8):
        got.append(c)
        if len(got) == 3:
            routing = ray_tpu.get(
                controller.get_routing.remote("replaysrc"), timeout=30)
            ray_tpu.kill(routing["replicas"][0])
    assert got == list(range(8))
    serve.delete("replaysrc")


def test_restarted_replica_raises_stream_lost(cluster):
    """next_chunk for a stream id the replica does not know must raise
    ReplicaStreamLostError (the failover trigger), never fake a clean
    end-of-stream."""
    from ray_tpu.serve._private import (
        CONTROLLER_NAME, SERVE_NAMESPACE, _is_replica_loss)

    @serve.deployment(name="loststream")
    def gen():
        yield 1

    serve.run(gen.bind())
    controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    routing = ray_tpu.get(
        controller.get_routing.remote("loststream"), timeout=30)
    replica = routing["replicas"][0]
    with pytest.raises(Exception) as ei:
        ray_tpu.get(replica.next_chunk.remote(424242), timeout=30)
    assert _is_replica_loss(ei.value)
    serve.delete("loststream")


def test_status_reports_replica_states(cluster):
    @serve.deployment(name="stately", num_replicas=2)
    def f(x):
        return x

    serve.run(f.bind())
    st = serve.status()["stately"]
    assert st["states"]["RUNNING"] == 2
    assert st["states"]["DRAINING"] == 0
    serve.delete("stately")


def test_llm_stream_resume_policy_rewrites_request():
    """Unit: the LLM failover policy appends produced tokens to the
    prompt, decrements the budget, aligns the sampling offset, and
    signals completion (None) on exhausted budget or EOS."""
    from ray_tpu.serve import llm_stream_resume

    args, kwargs = llm_stream_resume(([1, 2], 8), {}, [5, 6, 7])
    assert args == ([1, 2, 5, 6, 7],)
    assert kwargs["max_new_tokens"] == 5
    assert kwargs["_produced_offset"] == 3
    # Positional temperature/eos_id/seed survive as kwargs.
    args, kwargs = llm_stream_resume(([1], 4, 0.9, 99, 7), {}, [3])
    assert args == ([1, 3],)
    assert kwargs["temperature"] == 0.9 and kwargs["eos_id"] == 99 \
        and kwargs["seed"] == 7
    # Budget exhausted -> the stream was already complete.
    assert llm_stream_resume(([1], 3), {}, [4, 5, 6]) is None
    # EOS emitted -> complete, even with budget left.
    assert llm_stream_resume(([1], 9), {"eos_id": 6}, [4, 6]) is None
