"""Multi-agent RL tests (reference: rllib/env/multi_agent_env.py +
MultiAgentBatch of policy/sample_batch.py + the policy-mapping machinery;
VERDICT r2 item 7: two-agent cooperative env where BOTH policies improve).

Marked slow: learning gates run minutes on a small host."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.multi_agent import (
    CooperativeMatchEnv,
    MultiAgentBatch,
    MultiAgentRolloutWorker,
)


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


def test_multi_agent_env_contract():
    env = CooperativeMatchEnv(num_envs=3, seed=0)
    obs = env.reset_all(0)
    assert set(obs) == {"a0", "a1"} and obs["a0"].shape == (3, 4)
    acts = {a: np.argmax(obs[a], axis=1) for a in env.agent_ids}  # optimal
    obs2, rew, term, trunc = env.step(acts)
    # Both correct everywhere: 1.0 + 0.5 cooperation bonus each.
    np.testing.assert_allclose(rew["a0"], 1.5)
    np.testing.assert_allclose(rew["a1"], 1.5)
    assert not term.any()


def test_multi_agent_rollout_routes_rows_per_policy():
    w = MultiAgentRolloutWorker(
        "coop-match", num_envs=4, rollout_fragment_length=8,
        policies={"shared": None},
        policy_mapping_fn=lambda aid: "shared")
    batch, metrics = w.sample()
    assert isinstance(batch, MultiAgentBatch)
    # Shared policy receives BOTH agents' rows: 2 * T * B.
    assert set(batch.policy_batches) == {"shared"}
    assert batch.policy_batches["shared"].count == 2 * 8 * 4
    assert batch.count == 8 * 4  # env steps, not agent rows
    assert set(metrics["per_agent_returns"]) == {"a0", "a1"}


@pytest.mark.slow
def test_multi_agent_ppo_both_policies_improve(cluster):
    """Independent policies on the cooperative env: each policy's mean
    return must clearly beat the random baseline (~4.5; optimum 24) and
    improve over its own first measurement."""
    from ray_tpu.rllib.ppo import PPOConfig

    cfg = (PPOConfig()
           .environment("coop-match")
           .rollouts(num_rollout_workers=1, num_envs_per_worker=16,
                     rollout_fragment_length=32)
           .multi_agent(policies=["p0", "p1"],
                        policy_mapping_fn=lambda aid:
                        {"a0": "p0", "a1": "p1"}[aid])
           .training(train_batch_size=1024, sgd_minibatch_size=256,
                     num_sgd_iter=6, lr=5e-3, entropy_coeff=0.003)
           .debugging(seed=7))
    algo = cfg.build()
    try:
        first, last = None, None
        for _ in range(12):
            r = algo.train()
            p0 = r.get("policy_reward_mean/p0")
            p1 = r.get("policy_reward_mean/p1")
            if p0 is None:
                continue
            if first is None:
                first = (p0, p1)
            last = (p0, p1)
            if last[0] >= 12.0 and last[1] >= 12.0:
                break
        assert last is not None
        assert last[0] >= 12.0 and last[1] >= 12.0, (first, last)
        assert last[0] > first[0] and last[1] > first[1], (first, last)
    finally:
        algo.stop()
