"""Dashboard REST API + job submission tests (reference:
dashboard/modules/job/tests/test_job_manager.py and the job REST surface
in dashboard/modules/job/job_head.py)."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from ray_tpu.cluster_utils import Cluster
from ray_tpu.dashboard.sdk import JobSubmissionClient, JobSubmissionError


@pytest.fixture(scope="module")
def dash(tmp_path_factory):
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "dashboard",
         "--address", cluster.address, "--host", "127.0.0.1", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    os.set_blocking(proc.stdout.fileno(), False)
    port, buf = None, ""
    deadline = time.time() + 90
    while time.time() < deadline:
        chunk = proc.stdout.read()
        if chunk:
            buf += chunk.decode("utf-8", "replace")
        if "dashboard listening on" in buf:
            port = int(buf.split("dashboard listening on ")[1]
                       .split()[0].rsplit(":", 1)[1])
            break
        if proc.poll() is not None:
            raise RuntimeError(f"dashboard died during startup: {buf}")
        time.sleep(0.2)
    assert port, f"dashboard never reported its port: {buf}"
    client = JobSubmissionClient(f"http://127.0.0.1:{port}")
    try:
        yield cluster, client, port
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        cluster.shutdown()


def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def test_state_endpoints(dash):
    cluster, client, port = dash
    nodes = _get_json(port, "/api/nodes")["result"]
    assert len(nodes) == 1 and nodes[0]["alive"]
    assert _get_json(port, "/api/overview")["result"]["cluster"][
        "nodes_alive"] == 1
    # the UI page itself
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=30) as r:
        assert b"ray_tpu dashboard" in r.read()


def test_job_success_joins_cluster(dash):
    cluster, client, port = dash
    # The entrypoint's ray_tpu.init() picks up RAY_TPU_ADDRESS and joins
    # the cluster that launched it.
    code = ("import ray_tpu; ray_tpu.init(); "
            "print('cpus', ray_tpu.cluster_resources().get('CPU')); "
            "print('sub', __import__('os').environ["
            "'RAY_TPU_JOB_SUBMISSION_ID'])")
    sub_id = client.submit_job(entrypoint=f"{sys.executable} -c \"{code}\"")
    rec = client.wait_until_finished(sub_id, timeout=180)
    logs = client.get_job_logs(sub_id)
    assert rec["status"] == "SUCCEEDED", logs
    assert "cpus 4.0" in logs
    assert f"sub {sub_id}" in logs


def test_job_failure(dash):
    cluster, client, port = dash
    sub_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import sys; sys.exit(3)'")
    rec = client.wait_until_finished(sub_id, timeout=120)
    assert rec["status"] == "FAILED"
    assert "exit code 3" in rec["message"]


def test_job_stop(dash):
    cluster, client, port = dash
    sub_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; print(\"up\", "
                   f"flush=True); time.sleep(600)'")
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(sub_id)["status"] == "RUNNING":
            break
        time.sleep(0.2)
    assert client.stop_job(sub_id)
    rec = client.wait_until_finished(sub_id, timeout=60)
    assert rec["status"] == "STOPPED"


def test_job_list_and_delete(dash):
    cluster, client, port = dash
    sub_id = client.submit_job(entrypoint="echo listed-job-marker")
    client.wait_until_finished(sub_id, timeout=120)
    assert any(r["submission_id"] == sub_id for r in client.list_jobs())
    assert "listed-job-marker" in client.get_job_logs(sub_id)
    assert client.delete_job(sub_id)
    assert not any(r["submission_id"] == sub_id for r in client.list_jobs())
    with pytest.raises(JobSubmissionError):
        client.get_job_status(sub_id)


def test_duplicate_submission_id_rejected(dash):
    cluster, client, port = dash
    sub_id = client.submit_job(entrypoint="echo one",
                               submission_id="fixed-id-1")
    client.wait_until_finished(sub_id, timeout=120)
    with pytest.raises(JobSubmissionError):
        client.submit_job(entrypoint="echo two", submission_id="fixed-id-1")


def test_cli_local_dump_and_global_gc(dash, tmp_path):
    """Ops commands (reference: scripts.py local_dump / global_gc)."""
    import io
    import tarfile
    from contextlib import redirect_stdout

    from ray_tpu.scripts import cli

    cluster, _client, _port = dash
    out = str(tmp_path / "dump.tar.gz")
    buf = io.StringIO()
    with redirect_stdout(buf):
        # Pin the dump to THIS cluster's session: mtime ordering over
        # /tmp is racy when other sessions churn concurrently.
        rc = cli.main(["local-dump", "--address", cluster.address,
                       "--out", out, "--session-dir",
                       cluster.session_dir])
    assert rc == 0
    with tarfile.open(out) as tar:
        names = tar.getnames()
    assert any("cluster_state.json" in n for n in names)
    assert any("logs" in n for n in names)

    with redirect_stdout(buf):
        rc = cli.main(["global-gc", "--address", cluster.address])
    assert rc == 0
    assert "gc.collect() ran" in buf.getvalue()
