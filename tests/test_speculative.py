"""Speculative decoding tests: token-exactness vs the non-speculative
engine (greedy and seeded sampling), paged-KV rollback invariants under
rejection storms, adaptive draft-length backoff/recovery, mixed
speculative/plain lanes in one verify step, burst atomicity and
mid-burst stop clamping, prefix-cache interaction (drafted blocks never
sealed until accepted), failover resume, and the chaos gate (replica
kill mid-burst resumes token-exact)."""

import queue

import pytest

import ray_tpu
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.inference import InferenceEngine, NgramProposer
from ray_tpu.inference.speculative import (DraftProposer,
                                           ModelDraftProposer,
                                           resolve_draft_proposer)


def _engine(spec_k=0, proposer="ngram", params=None, **kw):
    kw.setdefault("max_lanes", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return InferenceEngine("gpt", "nano", params=params, auto_start=False,
                           seed=0, spec_k=spec_k, draft_proposer=proposer,
                           **kw)


class OracleProposer(DraftProposer):
    """Drafts the exact continuation a reference run produced — 100%
    acceptance by construction (single-request engines only)."""

    def __init__(self, prompt, continuation):
        self.prompt = list(prompt)
        self.cont = [int(t) for t in continuation]
        self.calls = []

    def propose(self, context, k):
        self.calls.append(k)
        pos = len(context) - len(self.prompt)
        return self.cont[pos:pos + k]


class AntiOracleProposer(OracleProposer):
    """Drafts a token guaranteed to DIFFER from the reference
    continuation at every position — 0% acceptance by construction."""

    def __init__(self, prompt, continuation, vocab):
        super().__init__(prompt, continuation)
        self.vocab = vocab

    def propose(self, context, k):
        return [(t + 1) % self.vocab
                for t in super().propose(context, k)]


# ---------------------------------------------------------------------------
# Proposer units
# ---------------------------------------------------------------------------

def test_ngram_proposer_prompt_lookup():
    p = NgramProposer(max_ngram=3)
    # Suffix [7, 8] occurred earlier; the most recent occurrence is
    # followed by [9, 1] — proposed verbatim, capped at k.
    ctx = [7, 8, 9, 1, 7, 8, 9, 1, 7, 8]
    assert p.propose(ctx, 4) == [9, 1, 7, 8]
    assert p.propose(ctx, 2) == [9, 1]
    assert p.propose([1, 2, 3, 4, 5], 4) == []      # nothing repeats
    assert p.propose([5], 4) == []                  # no suffix to match
    # min_ngram=1 catches a constant stream.
    assert p.propose([3, 3, 3], 2) == [3, 3]
    with pytest.raises(ValueError, match="min_ngram"):
        NgramProposer(max_ngram=0)


def test_resolve_draft_proposer():
    assert isinstance(resolve_draft_proposer("ngram"), NgramProposer)
    p = NgramProposer()
    assert resolve_draft_proposer(p) is p
    with pytest.raises(ValueError, match="unknown draft proposer"):
        resolve_draft_proposer("nope")


# ---------------------------------------------------------------------------
# Token-exactness vs the non-speculative engine
# ---------------------------------------------------------------------------

def test_spec_token_exact_greedy_and_sampled():
    plain = _engine()
    spec = _engine(spec_k=4, params=plain.params)
    # Repetitive prompt: n-gram drafting fires and bursts really commit.
    prompt = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
    greedy = plain.generate(prompt, 24)
    assert spec.generate(prompt, 24) == greedy
    st = spec.stats()
    assert st["spec_drafted_tokens"] > 0
    assert st["spec_steps"] > 0
    # Seeded sampling: per-position keys are fold_in(seed, produced+j),
    # identical to the keys the plain engine folds step by step.
    sampled = plain.generate(prompt, 24, temperature=0.8, seed=123)
    assert spec.generate(prompt, 24, temperature=0.8, seed=123) == sampled


def test_spec_emits_multi_token_bursts():
    plain = _engine(max_lanes=1)
    full = plain.generate([5, 6, 7], 16)
    spec = _engine(spec_k=4, params=plain.params, max_lanes=1,
                   proposer=OracleProposer([5, 6, 7], full))
    assert spec.generate([5, 6, 7], 16) == full
    st = spec.stats()
    # Perfect drafts: strictly more than one token per verify step.
    assert st["spec_accepted_per_step"] > 1.5
    assert st["spec_steps"] < len(full)


# ---------------------------------------------------------------------------
# Paged-KV rollback under rejection storms
# ---------------------------------------------------------------------------

def test_rejection_storm_rolls_back_blocks():
    plain = _engine(max_lanes=1, prefix_cache=False)
    prompt = [2, 3, 4]
    full = plain.generate(prompt, 20)
    vocab = plain.config.vocab_size
    spec = _engine(spec_k=4, params=plain.params, max_lanes=1,
                   prefix_cache=False,
                   proposer=AntiOracleProposer(prompt, full, vocab),
                   spec_adaptive=False)     # keep drafting k=4 junk
    h = spec.submit(prompt, 20)
    while spec.step():
        # Rollback invariant after EVERY commit: a live lane owns
        # exactly the blocks its committed length needs — rejected
        # draft tokens never leave stray tail blocks behind.
        for lane, req in enumerate(spec._lanes):
            if req is None:
                continue
            assert len(spec.cache.lane_blocks(lane)) == \
                spec.cache.blocks_needed(int(spec.cache.seq_lens[lane]))
    assert h.tokens() == full               # still token-exact
    st = spec.stats()
    assert st["spec_drafted_tokens"] > 0
    assert st["spec_accepted_tokens"] == 0  # every draft rejected
    # Full conservation: everything returned to the free list.
    assert spec.cache.allocator.num_free == spec.cache.allocator.num_blocks


def test_adaptive_k_backs_off_and_recovers():
    plain = _engine(max_lanes=1)
    prompt = [9, 8, 7]
    full = plain.generate(prompt, 40)
    vocab = plain.config.vocab_size
    # Phase 1: guaranteed rejection — the per-lane draft length halves
    # from 8 down to the floor of 1.
    anti = AntiOracleProposer(prompt, full, vocab)
    spec = _engine(spec_k=8, params=plain.params, max_lanes=1,
                   proposer=anti)
    assert spec.generate(prompt, 16) == full[:16]
    assert anti.calls[0] == 8
    assert 1 in anti.calls                  # reached the floor
    assert all(b <= a for a, b in zip(anti.calls, anti.calls[1:]))
    # Phase 2: guaranteed acceptance — the draft length grows back by
    # one per fully-accepted burst (the tail call may shrink again as
    # the remaining token budget clamps the draft).
    oracle = OracleProposer(prompt, full)
    spec = _engine(spec_k=8, params=plain.params, max_lanes=1,
                   proposer=oracle)
    h = spec.submit(prompt, 40)
    h._req.spec_k = 1                       # start the lane at the floor
    while spec.step():
        pass
    assert h.tokens() == full
    assert oracle.calls[0] == 1
    peak = max(oracle.calls)
    assert peak >= 6                        # climbed well off the floor
    climb = oracle.calls[:oracle.calls.index(peak) + 1]
    assert climb == sorted(climb)           # monotone recovery


# ---------------------------------------------------------------------------
# Mixed speculative / plain lanes in one step
# ---------------------------------------------------------------------------

def test_mixed_spec_and_plain_lanes_share_a_step():
    class Selective(DraftProposer):
        """Drafts only for contexts starting with the marker token, so
        one lane speculates while its neighbour decodes plainly in the
        SAME verify dispatch."""

        def __init__(self, marker, inner):
            self.marker = marker
            self.inner = inner

        def propose(self, context, k):
            if context[0] != self.marker:
                return []
            return self.inner.propose(context, k)

    plain = _engine()
    p_spec = [4, 5, 4, 5, 4, 5, 4]
    p_plain = [9, 2, 6]
    a = plain.generate(p_spec, 12)
    b = plain.generate(p_plain, 12)
    spec = _engine(spec_k=3, params=plain.params,
                   proposer=Selective(4, NgramProposer()))
    dispatches = []
    orig = spec._build_batch

    def snoop(live, t):
        batch, chunks = orig(live, t)
        dispatches.append((t, dict(chunks)))
        return batch, chunks

    spec._build_batch = snoop
    h1 = spec.submit(p_spec, 12)
    h2 = spec.submit(p_plain, 12)
    while spec.step():
        pass
    assert h1.tokens() == a
    assert h2.tokens() == b
    assert spec.stats()["spec_drafted_tokens"] > 0
    # At least one verify dispatch (t > 1) carried BOTH a drafting lane
    # (chunk > 1) and a draftless lane riding at chunk=1.
    assert any(t > 1 and len(ch) == 2
               and min(ch.values()) == 1 and max(ch.values()) > 1
               for t, ch in dispatches)


# ---------------------------------------------------------------------------
# Burst atomicity + mid-burst stop conditions
# ---------------------------------------------------------------------------

def test_burst_commits_atomically():
    plain = _engine(max_lanes=1)
    prompt = [3, 1, 4]
    full = plain.generate(prompt, 12)
    spec = _engine(spec_k=4, params=plain.params, max_lanes=1,
                   proposer=OracleProposer(prompt, full))
    h = spec.submit(prompt, 12)
    items = []
    while spec.step():
        # Drain the stream queue between steps: each element is what one
        # commit made visible — a burst arrives as ONE list item, never
        # as a partially delivered draft.
        while True:
            try:
                items.append(h._req.out.get_nowait())
            except queue.Empty:
                break
    flat = []
    for it in items:
        if isinstance(it, list):
            flat.extend(it)
        elif isinstance(it, int):
            flat.append(it)               # (skips the _DONE sentinel)
    assert flat == full
    assert any(isinstance(it, list) and len(it) > 1 for it in items)


def test_eos_mid_burst_clamps_over_generated_drafts():
    plain = _engine(max_lanes=1)
    prompt = [6, 2, 8]
    full = plain.generate(prompt, 16)
    eos = full[4]                           # lands mid-burst under k=4
    expect = plain.generate(prompt, 16, eos_id=eos)
    spec = _engine(spec_k=4, params=plain.params, max_lanes=1,
                   proposer=OracleProposer(prompt, full))
    h = spec.submit(prompt, 16, eos_id=eos)
    while spec.step():
        pass
    got = h.tokens()
    assert got == expect
    assert got[-1] == eos
    assert h.finish_reason == "eos"
    # Tokens drafted past the stop were discarded, not streamed.
    assert len(got) == full.index(eos) + 1


def test_max_new_tokens_mid_burst_is_exact():
    plain = _engine(max_lanes=1)
    prompt = [1, 7, 3]
    full = plain.generate(prompt, 16)
    spec = _engine(spec_k=4, params=plain.params, max_lanes=1,
                   proposer=OracleProposer(prompt, full))
    h = spec.submit(prompt, 6)              # budget lands mid-burst
    while spec.step():
        pass
    assert h.tokens() == full[:6]
    assert h.finish_reason == "length"


# ---------------------------------------------------------------------------
# Prefix-cache interaction
# ---------------------------------------------------------------------------

def test_drafted_blocks_never_sealed_until_accepted():
    plain = _engine(max_lanes=1)
    prompt = [2, 2, 3] * 6          # 2 full blocks + 2 tokens to prefill
    full = plain.generate(prompt, 16)
    spec = _engine(spec_k=4, params=plain.params, max_lanes=1,
                   proposer=OracleProposer(prompt, full))
    h = spec.submit(prompt, 16)
    while spec.step():
        # Sealing is bounded by the COMMITTED length: a block that
        # still holds unverified draft K/V can never enter the
        # content-addressed index.
        for lane, req in enumerate(spec._lanes):
            if req is not None:
                assert spec.cache._lane_sealed[lane] * \
                    spec.cache.block_size <= int(spec.cache.seq_lens[lane])
    assert h.tokens() == full
    # The sealed chain is the same one the plain engine would build, so
    # a second identical prompt admits through the prefix cache and
    # still decodes token-exact.
    plain.generate(prompt, 16)
    assert spec.cache.num_indexed_blocks == plain.cache.num_indexed_blocks
    spec2 = _engine(spec_k=4, params=plain.params, max_lanes=1,
                    proposer=OracleProposer(prompt, full))
    spec2_full = spec2.generate(prompt, 16)
    hits0 = spec2.stats()["prefix_hits"]
    assert spec2.generate(prompt, 16) == spec2_full == full
    assert spec2.stats()["prefix_hits"] == hits0 + 1


# ---------------------------------------------------------------------------
# Failover building blocks
# ---------------------------------------------------------------------------

def test_sample_offset_resume_is_seed_consistent_with_spec():
    plain = _engine()
    prompt = [1, 2, 1, 2, 1, 2]
    full = plain.generate(prompt, 10, temperature=0.9, seed=42)
    spec = _engine(spec_k=4, params=plain.params)
    part = spec.generate(prompt, 3, temperature=0.9, seed=42)
    assert part == full[:3]
    # Resume mid-stream: produced tokens re-enter as prompt and
    # sample_offset keeps the key counter at the ORIGINAL position even
    # though verify steps now sample several positions at once.
    h = spec.submit(prompt + part, max_new_tokens=len(full) - 3,
                    temperature=0.9, seed=42, sample_offset=3)
    while spec.step():
        pass
    assert h.tokens() == full[3:]


def test_model_draft_proposer_self_draft_accepts():
    plain = _engine(max_lanes=1)
    # The draft model IS the target model (same params): greedy drafts
    # equal greedy verification, so every draft is accepted and the
    # output stays token-exact.
    spec = _engine(spec_k=3, params=plain.params, max_lanes=1,
                   proposer=ModelDraftProposer(
                       "gpt", "nano", params=plain.params, window=32))
    prompt = [4, 9, 1]
    assert spec.generate(prompt, 10) == plain.generate(prompt, 10)
    st = spec.stats()
    assert st["spec_accepted_tokens"] == st["spec_drafted_tokens"] > 0
    assert st["spec_accepted_per_step"] > 1.5


# ---------------------------------------------------------------------------
# Chaos gate: replica kill mid-burst resumes token-exact
# ---------------------------------------------------------------------------

@pytest.fixture
def serve_chaos_cluster(request):
    cfg = dict(getattr(request, "param", {}))
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    from ray_tpu import serve
    serve.start()
    try:
        yield info
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        from ray_tpu.serve import _private as sp
        with sp._router_states_lock:
            sp._router_states.clear()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


def _metric(name):
    from ray_tpu.util import metrics
    return metrics.read(name) or 0.0


@pytest.mark.chaos
@pytest.mark.parametrize(
    "serve_chaos_cluster",
    [{"chaos_enabled": True, "chaos_seed": 31,
      # Scripted: every replica incarnation dies at its 4th serve event
      # — mid-generation, and with spec_k=4 bursts mid-BURST: the lane
      # is killed between a burst's commit and the stream draining it.
      "chaos_kill_replica_salts": "*",
      "chaos_kill_replica_at": 4,
      "chaos_max_faults": 1}],
    indirect=True)
def test_replica_kill_mid_burst_resumes_token_exact(serve_chaos_cluster):
    from ray_tpu import serve
    prompt, budget = [1, 2, 3, 1, 2, 3, 1, 2], 8
    expected = InferenceEngine("gpt", "nano", seed=0).generate(
        prompt, budget)
    handle = serve.run(serve.LLMDeployment.options(
        name="llm_spec_chaos").bind(model="gpt", config="nano",
                                    max_lanes=4, seed=0,
                                    speculative=True, spec_k=4))
    before = _metric("serve_stream_failovers")
    got = list(handle.options("generate",
                              failover=serve.llm_stream_resume)
               .stream(prompt, budget))
    assert got == expected
    assert _metric("serve_stream_failovers") - before >= 1
