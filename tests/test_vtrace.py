"""V-trace unit tests against hand-computed references (PR 20 satellite).

`rllib/vtrace.py` is the correction that licenses the stale-tolerant
learner in `ray_tpu/rl/` — these tests pin its math to literal
hand-worked numbers and to an independent numpy recursion, so a refactor
of the lax.scan cannot silently bend the off-policy targets:

- on-policy (behavior == target): rhos == cs == 1 and vs_t must equal
  the plain discounted n-step return bootstrapped with V;
- clipped-rho off-policy: a tiny T=2 case worked out by hand on paper,
  asserted to the digit;
- general off-policy: random fragments vs a per-env python recursion of
  Espeholt et al. (2018) eq. (1) with explicit min(rho_bar, .) /
  min(c_bar, .) clipping;
- termination masking: a zero discount at t cuts all credit flow across
  the boundary.
"""

import numpy as np
import pytest

from ray_tpu.rllib.vtrace import vtrace


def _np_vtrace(behavior_logp, target_logp, rewards, discounts, values,
               bootstrap, rho_bar=1.0, c_bar=1.0):
    """Independent reference: the Espeholt et al. recursion in plain
    python, one env at a time."""
    T, B = rewards.shape
    rhos = np.exp(target_logp - behavior_logp)
    crho = np.minimum(rho_bar, rhos)
    cs = np.minimum(c_bar, rhos)
    vs = np.zeros((T, B), np.float64)
    for b in range(B):
        acc = 0.0
        for t in range(T - 1, -1, -1):
            v_tp1 = values[t + 1, b] if t + 1 < T else bootstrap[b]
            delta = crho[t, b] * (rewards[t, b]
                                  + discounts[t, b] * v_tp1 - values[t, b])
            acc = delta + discounts[t, b] * cs[t, b] * acc
            vs[t, b] = values[t, b] + acc
    vs_tp1 = np.concatenate([vs[1:], bootstrap[None]], axis=0)
    pg = crho * (rewards + discounts * vs_tp1 - values)
    return vs, pg


def test_vtrace_hand_computed_clipped_rho_case():
    """T=2, B=1, worked by hand: gamma=0.9, values (1, 2), bootstrap 3,
    rewards (0.5, 1), rhos (2, 0.5) -> clipped rhos (1, 0.5).

      delta_1 = 0.5 * (1.0 + 0.9*3.0 - 2.0)        = 0.85
      delta_0 = 1.0 * (0.5 + 0.9*2.0 - 1.0)        = 1.30
      vs_1    = 2.0 + 0.85                          = 2.85
      vs_0    = 1.0 + 1.30 + 0.9 * 1.0 * 0.85      = 3.065
      pg_0    = 1.0 * (0.5 + 0.9*2.85 - 1.0)       = 2.065
      pg_1    = 0.5 * (1.0 + 0.9*3.0 - 2.0)        = 0.85
    """
    import jax.numpy as jnp

    behavior = np.log(np.array([[1.0], [1.0]], np.float32))
    target = np.log(np.array([[2.0], [0.5]], np.float32))
    rewards = np.array([[0.5], [1.0]], np.float32)
    discounts = np.full((2, 1), 0.9, np.float32)
    values = np.array([[1.0], [2.0]], np.float32)
    bootstrap = np.array([3.0], np.float32)

    out = vtrace(jnp.asarray(behavior), jnp.asarray(target),
                 jnp.asarray(rewards), jnp.asarray(discounts),
                 jnp.asarray(values), jnp.asarray(bootstrap),
                 clip_rho_threshold=1.0, clip_c_threshold=1.0)
    np.testing.assert_allclose(np.asarray(out.vs),
                               [[3.065], [2.85]], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.pg_advantages),
                               [[2.065], [0.85]], rtol=1e-5)


def test_vtrace_on_policy_equals_nstep_return_and_td_advantage():
    """behavior == target: vs_t is the discounted n-step return and the
    pg advantage collapses to the 1-step TD error against vs."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    T, B = 10, 3
    logp = rng.normal(size=(T, B)).astype(np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    bootstrap = rng.normal(size=B).astype(np.float32)
    discounts = np.full((T, B), 0.97, np.float32)

    out = vtrace(jnp.asarray(logp), jnp.asarray(logp), jnp.asarray(rewards),
                 jnp.asarray(discounts), jnp.asarray(values),
                 jnp.asarray(bootstrap))
    vs = np.asarray(out.vs)

    expected = np.empty_like(values)
    nxt = bootstrap.astype(np.float64)
    for t in range(T - 1, -1, -1):
        expected[t] = rewards[t] + discounts[t] * nxt
        nxt = expected[t]
    np.testing.assert_allclose(vs, expected, rtol=1e-4, atol=1e-4)

    vs_tp1 = np.concatenate([vs[1:], bootstrap[None]], axis=0)
    np.testing.assert_allclose(np.asarray(out.pg_advantages),
                               rewards + discounts * vs_tp1 - values,
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("rho_bar,c_bar", [(1.0, 1.0), (2.0, 0.9),
                                           (0.5, 0.5)])
def test_vtrace_off_policy_matches_python_recursion(rho_bar, c_bar):
    """Random off-policy fragments vs the independent per-env numpy
    recursion, across clipping thresholds (including c_bar != rho_bar)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(int(rho_bar * 10 + c_bar))
    T, B = 9, 4
    behavior = rng.normal(size=(T, B)).astype(np.float32)
    target = (behavior + rng.normal(scale=0.7, size=(T, B))).astype(
        np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    bootstrap = rng.normal(size=B).astype(np.float32)
    dones = rng.random((T, B)) < 0.2
    discounts = (0.99 * (~dones)).astype(np.float32)

    out = vtrace(jnp.asarray(behavior), jnp.asarray(target),
                 jnp.asarray(rewards), jnp.asarray(discounts),
                 jnp.asarray(values), jnp.asarray(bootstrap),
                 clip_rho_threshold=rho_bar, clip_c_threshold=c_bar)
    ref_vs, ref_pg = _np_vtrace(behavior, target, rewards, discounts,
                                values, bootstrap, rho_bar, c_bar)
    np.testing.assert_allclose(np.asarray(out.vs), ref_vs,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.pg_advantages), ref_pg,
                               rtol=1e-4, atol=1e-4)


def test_vtrace_zero_discount_stops_credit_flow():
    """A terminal at t (discount 0) makes vs before the boundary
    independent of everything after it — the episode seam is absolute."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    T, B = 8, 2
    behavior = rng.normal(size=(T, B)).astype(np.float32)
    target = rng.normal(size=(T, B)).astype(np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    bootstrap = rng.normal(size=B).astype(np.float32)
    discounts = np.full((T, B), 0.99, np.float32)
    discounts[3] = 0.0  # terminal transition at t=3

    out1 = vtrace(jnp.asarray(behavior), jnp.asarray(target),
                  jnp.asarray(rewards), jnp.asarray(discounts),
                  jnp.asarray(values), jnp.asarray(bootstrap))
    # Scramble everything after the terminal; vs[:4] must not move.
    rewards2 = rewards.copy()
    rewards2[4:] += 100.0
    values2 = values.copy()
    values2[4:] -= 50.0
    out2 = vtrace(jnp.asarray(behavior), jnp.asarray(target),
                  jnp.asarray(rewards2), jnp.asarray(discounts),
                  jnp.asarray(values2), jnp.asarray(bootstrap * 0 + 99))
    np.testing.assert_allclose(np.asarray(out1.vs)[:4],
                               np.asarray(out2.vs)[:4],
                               rtol=1e-4, atol=1e-4)
