"""GCS persistence tests (reference: gcs/store_client/ pluggable storage,
GCS fault tolerance with Redis-backed tables; here sqlite rows per record)."""

import asyncio
import os
import tempfile


def test_gcs_persistence_roundtrip():
    """GCS restart with sqlite-backed tables keeps actors/PGs/KV/job
    counter AND node membership (reference: redis_store_client.h GCS
    fault tolerance; VERDICT r2 item 9)."""
    from ray_tpu._private.gcs import GcsServer, GcsTableStorage
    from ray_tpu._private.ids import ActorID, JobID, NodeID
    from ray_tpu._private.protocol import ActorInfo, NodeInfo

    path = os.path.join(tempfile.mkdtemp(), "gcs.sqlite")
    node_id = NodeID.from_random()

    async def first_life():
        g = GcsServer(storage=GcsTableStorage(path))
        await g.kv.kv_put({"ns": "fn", "key": "k1", "value": b"blob"})
        info = ActorInfo(actor_id=ActorID.of(JobID(b"\x01\x00\x00\x00")),
                         name="persisted", class_name="A", state="DEAD")
        g.actors[info.actor_id] = info
        g._mark_dirty("actors", info.actor_id)
        g.named_actors[("default", "persisted")] = info.actor_id
        g._mark_dirty("named_actors", ("default", "persisted"))
        g.nodes[node_id] = NodeInfo(node_id=node_id,
                                    address="127.0.0.1:7777",
                                    store_path="/dev/shm/x")
        g._mark_dirty("nodes", node_id)
        g.next_job = 7
        g._mark_dirty("meta", None)
        await asyncio.sleep(0.5)   # debounce window
        assert os.path.exists(path)
        g.storage.close()

    asyncio.run(first_life())

    async def second_life():
        g2 = GcsServer(storage=GcsTableStorage(path))
        g2._restore()
        assert g2.next_job == 7
        assert ("default", "persisted") in g2.named_actors
        assert any(a.name == "persisted" for a in g2.actors.values())
        assert (await g2.kv.kv_get({"ns": "fn", "key": "k1"}))["value"] == b"blob"
        # Node membership survives restart (restored alive, fresh
        # heartbeat stamp so the death sweep gives it a grace window).
        assert node_id in g2.nodes and g2.nodes[node_id].alive
        assert node_id in g2.node_heartbeat
        await asyncio.sleep(0.1)  # let _reconcile_restored task run
        g2.storage.close()

    asyncio.run(second_life())


def test_gcs_persistence_writes_are_o_delta():
    """A mutation flush writes only the dirtied rows + constant meta, not
    the whole table (VERDICT r2 weak 4: whole-state-blob-per-mutation
    becomes the control-plane bottleneck at 40k-actor scale)."""
    from ray_tpu._private.gcs import GcsServer, GcsTableStorage
    from ray_tpu._private.ids import ActorID, JobID
    from ray_tpu._private.protocol import ActorInfo

    path = os.path.join(tempfile.mkdtemp(), "gcs.sqlite")

    async def run():
        g = GcsServer(storage=GcsTableStorage(path))
        jid = JobID(b"\x01\x00\x00\x00")
        infos = []
        for _ in range(200):
            info = ActorInfo(actor_id=ActorID.of(jid), state="DEAD")
            g.actors[info.actor_id] = info
            g._mark_dirty("actors", info.actor_id)
            infos.append(info)
        await asyncio.sleep(0.5)   # flush the bulk load
        before = g.storage.write_ops
        # One record changes; the flush must not rewrite the other 199.
        infos[0].state = "ALIVE"
        g._bump("actors", infos[0].actor_id)
        await asyncio.sleep(0.5)
        delta = g.storage.write_ops - before
        assert 1 <= delta <= 3, f"expected O(delta) rows, wrote {delta}"
        # Deleted KV keys stay deleted after restore.
        await g.kv.kv_put({"ns": "a", "key": "gone", "value": b"x"})
        await g.kv.kv_del({"ns": "a", "key": "gone"})
        await asyncio.sleep(0.5)
        g.storage.close()

    asyncio.run(run())

    async def check():
        from ray_tpu._private.gcs import GcsServer, GcsTableStorage
        g2 = GcsServer(storage=GcsTableStorage(path))
        g2._restore()
        assert len(g2.actors) == 200
        assert (await g2.kv.kv_get({"ns": "a", "key": "gone"}))["value"] is None
        await asyncio.sleep(0.1)
        g2.storage.close()

    asyncio.run(check())
