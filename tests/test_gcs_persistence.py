"""GCS persistence tests (reference: gcs/store_client/ pluggable storage,
GCS fault tolerance with Redis-backed tables)."""

import asyncio
import os
import tempfile

def test_gcs_persistence_roundtrip():
    """GCS restart with file-backed tables keeps actors/PGs/KV/job counter
    (reference: redis_store_client.h GCS fault tolerance)."""
    from ray_tpu._private.gcs import GcsServer, GcsTableStorage
    from ray_tpu._private.ids import ActorID, JobID
    from ray_tpu._private.protocol import ActorInfo

    path = os.path.join(tempfile.mkdtemp(), "gcs.snapshot")

    async def first_life():
        g = GcsServer(storage=GcsTableStorage(path))
        g.kv.on_change = g._schedule_persist
        await g.kv.kv_put({"ns": "fn", "key": "k1", "value": b"blob"})
        info = ActorInfo(actor_id=ActorID.of(JobID(b"\x01\x00\x00\x00")),
                         name="persisted", class_name="A", state="DEAD")
        g.actors[info.actor_id] = info
        g.named_actors[("default", "persisted")] = info.actor_id
        g.next_job = 7
        g._bump()
        await asyncio.sleep(0.5)   # debounce window
        assert os.path.exists(path)

    asyncio.run(first_life())

    async def second_life():
        g2 = GcsServer(storage=GcsTableStorage(path))
        g2._restore()
        assert g2.next_job == 7
        assert ("default", "persisted") in g2.named_actors
        assert any(a.name == "persisted" for a in g2.actors.values())
        assert (await g2.kv.kv_get({"ns": "fn", "key": "k1"}))["value"] == b"blob"
        await asyncio.sleep(0.1)  # let _reconcile_restored task run

    asyncio.run(second_life())

