"""GCS persistence tests (reference: gcs/store_client/ pluggable storage,
GCS fault tolerance with Redis-backed tables; here sqlite rows per record)."""

import asyncio
import os
import subprocess
import sys
import tempfile


def test_gcs_persistence_roundtrip():
    """GCS restart with sqlite-backed tables keeps actors/PGs/KV/job
    counter AND node membership (reference: redis_store_client.h GCS
    fault tolerance; VERDICT r2 item 9)."""
    from ray_tpu._private.gcs import GcsServer, GcsTableStorage
    from ray_tpu._private.ids import ActorID, JobID, NodeID
    from ray_tpu._private.protocol import ActorInfo, NodeInfo

    path = os.path.join(tempfile.mkdtemp(), "gcs.sqlite")
    node_id = NodeID.from_random()

    async def first_life():
        g = GcsServer(storage=GcsTableStorage(path))
        await g.kv.kv_put({"ns": "fn", "key": "k1", "value": b"blob"})
        info = ActorInfo(actor_id=ActorID.of(JobID(b"\x01\x00\x00\x00")),
                         name="persisted", class_name="A", state="DEAD")
        g.actors[info.actor_id] = info
        g._mark_dirty("actors", info.actor_id)
        g.named_actors[("default", "persisted")] = info.actor_id
        g._mark_dirty("named_actors", ("default", "persisted"))
        g.nodes[node_id] = NodeInfo(node_id=node_id,
                                    address="127.0.0.1:7777",
                                    store_path="/dev/shm/x")
        g._mark_dirty("nodes", node_id)
        g.next_job = 7
        g._mark_dirty("meta", None)
        await asyncio.sleep(0.5)   # debounce window
        assert os.path.exists(path)
        g.storage.close()

    asyncio.run(first_life())

    async def second_life():
        g2 = GcsServer(storage=GcsTableStorage(path))
        g2._restore()
        assert g2.next_job == 7
        assert ("default", "persisted") in g2.named_actors
        assert any(a.name == "persisted" for a in g2.actors.values())
        assert (await g2.kv.kv_get({"ns": "fn", "key": "k1"}))["value"] == b"blob"
        # Node membership survives restart (restored alive, fresh
        # heartbeat stamp so the death sweep gives it a grace window).
        assert node_id in g2.nodes and g2.nodes[node_id].alive
        assert node_id in g2.node_heartbeat
        await asyncio.sleep(0.1)  # let _reconcile_restored task run
        g2.storage.close()

    asyncio.run(second_life())


def test_gcs_persistence_writes_are_o_delta():
    """A mutation flush writes only the dirtied rows + constant meta, not
    the whole table (VERDICT r2 weak 4: whole-state-blob-per-mutation
    becomes the control-plane bottleneck at 40k-actor scale)."""
    from ray_tpu._private.gcs import GcsServer, GcsTableStorage
    from ray_tpu._private.ids import ActorID, JobID
    from ray_tpu._private.protocol import ActorInfo

    path = os.path.join(tempfile.mkdtemp(), "gcs.sqlite")

    async def run():
        g = GcsServer(storage=GcsTableStorage(path))
        jid = JobID(b"\x01\x00\x00\x00")
        infos = []
        for _ in range(200):
            info = ActorInfo(actor_id=ActorID.of(jid), state="DEAD")
            g.actors[info.actor_id] = info
            g._mark_dirty("actors", info.actor_id)
            infos.append(info)
        await asyncio.sleep(0.5)   # flush the bulk load
        before = g.storage.write_ops
        # One record changes; the flush must not rewrite the other 199.
        infos[0].state = "ALIVE"
        g._bump("actors", infos[0].actor_id)
        await asyncio.sleep(0.5)
        delta = g.storage.write_ops - before
        assert 1 <= delta <= 3, f"expected O(delta) rows, wrote {delta}"
        # Deleted KV keys stay deleted after restore.
        await g.kv.kv_put({"ns": "a", "key": "gone", "value": b"x"})
        await g.kv.kv_del({"ns": "a", "key": "gone"})
        await asyncio.sleep(0.5)
        g.storage.close()

    asyncio.run(run())

    async def check():
        from ray_tpu._private.gcs import GcsServer, GcsTableStorage
        g2 = GcsServer(storage=GcsTableStorage(path))
        g2._restore()
        assert len(g2.actors) == 200
        assert (await g2.kv.kv_get({"ns": "a", "key": "gone"}))["value"] is None
        await asyncio.sleep(0.1)
        g2.storage.close()

    asyncio.run(check())


# ---------------------------------------------------------------------------
# Post-restart reconciliation (_reconcile_restored)
# ---------------------------------------------------------------------------

def _fresh_gcs():
    from ray_tpu._private.gcs import GcsServer, GcsTableStorage
    return GcsServer(storage=GcsTableStorage(None))


def test_reconcile_restored_pings_alive_actors():
    """A restored-ALIVE actor is pinged at its recorded address: a
    reachable one is left untouched, an unreachable one goes through the
    normal interruption/restart path WITHOUT the GCS pretending its
    worker survived (reference: RayletNotifyGCSRestart)."""
    from ray_tpu._private.ids import ActorID, JobID
    from ray_tpu._private.protocol import ActorInfo
    from ray_tpu._private.rpc import RpcServer

    async def run():
        g = _fresh_gcs()
        scheduled = []

        async def fake_schedule(actor):
            scheduled.append(actor.actor_id)

        g._schedule_actor = fake_schedule

        # A live "worker" answering CoreWorker.Ping.
        server = RpcServer()

        async def ping(req):
            return {"ok": True}

        server.register("CoreWorker", "Ping", ping)
        port = await server.start(0)

        jid = JobID(b"\x01\x00\x00\x00")
        alive_ok = ActorInfo(actor_id=ActorID.of(jid), state="ALIVE",
                             address=f"127.0.0.1:{port}", max_restarts=3)
        alive_gone = ActorInfo(actor_id=ActorID.of(jid), state="ALIVE",
                               address="127.0.0.1:1", max_restarts=3)
        g.actors[alive_ok.actor_id] = alive_ok
        g.actors[alive_gone.actor_id] = alive_gone
        await g._reconcile_restored()
        await asyncio.sleep(0.05)  # drain the ensure_future'd schedule

        # Reachable: untouched — no restart burned, still ALIVE there.
        assert alive_ok.state == "ALIVE"
        assert alive_ok.num_restarts == 0
        assert alive_ok.actor_id not in scheduled
        # Unreachable: interrupted through the restart path.
        assert alive_gone.state == "RESTARTING"
        assert alive_gone.num_restarts == 1
        assert alive_gone.actor_id in scheduled
        await server.stop()
        g.storage.close()

    asyncio.run(run())


def test_reconcile_restored_resumes_pending_without_burning_restart():
    """PENDING/RESTARTING actors restored from the tables never FAILED —
    they resume scheduling with the restart budget untouched."""
    from ray_tpu._private.ids import ActorID, JobID
    from ray_tpu._private.protocol import ActorInfo

    async def run():
        g = _fresh_gcs()
        scheduled = []

        async def fake_schedule(actor):
            scheduled.append(actor.actor_id)

        g._schedule_actor = fake_schedule
        jid = JobID(b"\x01\x00\x00\x00")
        pending = ActorInfo(actor_id=ActorID.of(jid), state="PENDING",
                            max_restarts=2)
        restarting = ActorInfo(actor_id=ActorID.of(jid), state="RESTARTING",
                               max_restarts=2, num_restarts=1)
        dead = ActorInfo(actor_id=ActorID.of(jid), state="DEAD")
        for a in (pending, restarting, dead):
            g.actors[a.actor_id] = a
        await g._reconcile_restored()
        await asyncio.sleep(0.05)

        assert pending.actor_id in scheduled
        assert restarting.actor_id in scheduled
        assert dead.actor_id not in scheduled
        # The budget is untouched: resuming is not a failure.
        assert pending.num_restarts == 0
        assert restarting.num_restarts == 1
        g.storage.close()

    asyncio.run(run())


def test_reconcile_restored_reschedules_pg_bundles():
    """Restored PGs lose their bundle placements (nodes re-register with
    fresh state after a head restart) and go back through scheduling."""
    from ray_tpu._private.ids import PlacementGroupID
    from ray_tpu._private.protocol import PlacementGroupInfo

    async def run():
        g = _fresh_gcs()
        rescheduled = []

        async def fake_schedule_pg(info):
            rescheduled.append(info.pg_id)

        g._schedule_pg = fake_schedule_pg
        pg = PlacementGroupInfo(
            pg_id=PlacementGroupID.from_random(),
            bundles=[{"CPU": 1}, {"CPU": 1}], state="CREATED",
            bundle_nodes=["stale-node", "stale-node"],
            bundle_addresses=["127.0.0.1:9", "127.0.0.1:9"])
        removed = PlacementGroupInfo(
            pg_id=PlacementGroupID.from_random(),
            bundles=[{"CPU": 1}], state="REMOVED")
        g.placement_groups[pg.pg_id] = pg
        g.placement_groups[removed.pg_id] = removed
        await g._reconcile_restored()
        await asyncio.sleep(0.05)

        assert pg.pg_id in rescheduled
        assert pg.state == "PENDING"
        assert pg.bundle_nodes == [None, None]
        assert pg.bundle_addresses == ["", ""]
        assert removed.pg_id not in rescheduled
        g.storage.close()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Crash-atomicity of the coalesced flush (scripted mid-flush kill)
# ---------------------------------------------------------------------------

_FLUSH_CRASH_CHILD = r"""
import os, sys
from ray_tpu._private.gcs import GcsTableStorage

path = sys.argv[1]
st = GcsTableStorage(path)
# Flush 0: committed baseline (the chaos ordinal for flush 0 passes).
st.write_rows([("t1", b"k0", b"v0")], [])
# Flush 1: multi-row coalesced write; the scripted kill fires after the
# executemany staged every row but BEFORE the transaction commits.
st.write_rows([("t1", b"k%d" % i, b"v%d" % i) for i in range(1, 9)], [])
print("survived", flush=True)  # must never be reached
"""


def test_mid_flush_kill_rolls_back_whole_flush(tmp_path):
    """Killing the GCS inside a persistence flush (after executemany,
    before COMMIT) must roll back the ENTIRE flush on restore — a torn
    prefix of the coalesced write would resurrect half a state
    transition.  Proves crash-atomicity of the batched-write path."""
    from ray_tpu._private.gcs import GcsTableStorage

    path = str(tmp_path / "gcs.sqlite")
    env = dict(os.environ)
    env.update({
        "RAY_TPU_CHAOS_ENABLED": "1",
        "RAY_TPU_CHAOS_SEED": "1",
        "RAY_TPU_CHAOS_KILL_GCS_FLUSH_AT": "1",
        # The child is "incarnation 0" of the head for salt purposes.
        "RAY_TPU_CHAOS_PROC_SALT": "gcs0",
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.run(
        [sys.executable, "-c", _FLUSH_CRASH_CHILD, path],
        env=env, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1, proc.stderr
    assert "survived" not in proc.stdout

    st = GcsTableStorage(path)
    state = st.load_all()
    st.close()
    assert state is not None
    # Flush 0 is durable; NO row of flush 1 leaked through the crash.
    assert set(state.get("t1", {})) == {b"k0"}
