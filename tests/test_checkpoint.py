"""Sharded async-checkpoint subsystem tests (ray_tpu/checkpoint/).

Coverage map (ISSUE acceptance criteria):

- sharded save/restore roundtrip with host-local chunk dedup
- elastic restore: save under a 4-device mesh, restore under 2- and
  1-device meshes — token-exact values, re-bound shardings
- crash-safe commit: uncommitted (torn) directories are never restored
  and are GC'd once a committed step overtakes them
- async save path: training overlaps I/O, wait_until_finished barrier,
  forced join on the next save, background errors surface at barriers
- CheckpointManager retention: keep-last-K and keep-best-by-metric
- air.Checkpoint interop (from_sharded_dir / tmp-dir registry cleanup)
- trainer e2e: workers reporting async SaveHandles through session
"""

import collections
import glob
import os
import pickle
import threading
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import ray_tpu
from ray_tpu.air import Checkpoint, CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.air import checkpoint as air_checkpoint
from ray_tpu.checkpoint import (
    AsyncCheckpointer, CheckpointManager, CheckpointWriteError, COMMIT_FILE,
    SaveHandle, checkpoint_metadata, is_committed, restore_sharded,
    save_sharded, sharded)
from ray_tpu.train import DataParallelTrainer

OptState = collections.namedtuple("OptState", ["mu", "nu", "count"])


def _mesh(n, axes=("data",), shape=None):
    devs = np.array(jax.devices()[:n])
    if shape is not None:
        devs = devs.reshape(shape)
    return Mesh(devs, axes)


def _sample_tree(mesh):
    """Train-state-shaped tree: sharded + replicated jax arrays, a
    namedtuple (optax idiom), a host numpy array, python scalars."""
    w = jax.device_put(
        np.arange(32, dtype=np.float32).reshape(8, 4),
        NamedSharding(mesh, P("data", "model")))
    b = jax.device_put(np.arange(4, dtype=np.float32),
                       NamedSharding(mesh, P()))
    mu = jax.device_put(
        np.arange(8, dtype=np.float32).reshape(8, 1) * 0.5,
        NamedSharding(mesh, P("data")))
    return {
        "params": {"w": w, "b": b},
        "opt_state": OptState(mu=mu, nu=np.full((3,), 2.5, np.float64),
                              count=np.int32(7)),
        "step": 42,
        "tag": "run-a",
    }


# ---------------------------------------------------------------------------
# Sharded save/restore core
# ---------------------------------------------------------------------------


def test_sharded_roundtrip_and_layout(tmp_path):
    mesh = _mesh(4, ("data", "model"), (2, 2))
    tree = _sample_tree(mesh)
    path = str(tmp_path / "ck")
    save_sharded(path, tree, save_id="i0", step=42,
                 metrics={"loss": 0.25})

    assert is_committed(path)
    assert os.path.isfile(os.path.join(path, "manifest.json"))
    assert os.path.isfile(os.path.join(path, COMMIT_FILE))
    meta = checkpoint_metadata(path)
    assert meta["step"] == 42
    assert meta["metrics"] == {"loss": 0.25}
    assert meta["save_id"] == "i0"

    out = restore_sharded(path)   # default: host numpy tree
    assert np.array_equal(out["params"]["w"],
                          np.asarray(tree["params"]["w"]))
    assert np.array_equal(out["params"]["b"],
                          np.asarray(tree["params"]["b"]))
    assert isinstance(out["opt_state"], OptState)   # class reconstructed
    assert np.array_equal(out["opt_state"].mu,
                          np.asarray(tree["opt_state"].mu))
    assert np.array_equal(out["opt_state"].nu, tree["opt_state"].nu)
    assert out["step"] == 42 and out["tag"] == "run-a"


def test_chunk_dedup_replicated_written_once(tmp_path):
    """A fully replicated array produces exactly ONE chunk file; a
    (2,2)-sharded array produces one per distinct shard."""
    mesh = _mesh(4, ("data", "model"), (2, 2))
    tree = {
        "sharded": jax.device_put(
            np.arange(16, dtype=np.float32).reshape(4, 4),
            NamedSharding(mesh, P("data", "model"))),
        "replicated": jax.device_put(np.arange(6, dtype=np.float32),
                                     NamedSharding(mesh, P())),
    }
    path = str(tmp_path / "ck")
    save_sharded(path, tree)
    # Leaf ids follow dict insertion order: a0 = sharded, a1 = replicated.
    assert len(glob.glob(os.path.join(path, "a0_c*.bin"))) == 4
    assert len(glob.glob(os.path.join(path, "a1_c*.bin"))) == 1
    out = restore_sharded(path)
    assert np.array_equal(out["sharded"], np.asarray(tree["sharded"]))
    assert np.array_equal(out["replicated"],
                          np.asarray(tree["replicated"]))


def test_bfloat16_roundtrip(tmp_path):
    import jax.numpy as jnp
    mesh = _mesh(2)
    x = jax.device_put(jnp.arange(16, dtype=jnp.bfloat16).reshape(8, 2),
                       NamedSharding(mesh, P("data")))
    path = str(tmp_path / "ck")
    save_sharded(path, {"x": x})
    out = restore_sharded(path)
    assert str(out["x"].dtype) == "bfloat16"
    assert np.array_equal(out["x"], np.asarray(x))


def test_elastic_restore_across_device_counts(tmp_path):
    """Acceptance criterion: a checkpoint saved under one mesh restores
    token-exactly under a different device count, with its saved logical
    spec re-bound to the current mesh's axes."""
    mesh4 = _mesh(4, ("data", "model"), (2, 2))
    tree = _sample_tree(mesh4)
    path = str(tmp_path / "ck")
    save_sharded(path, tree)
    want_w = np.asarray(tree["params"]["w"])
    want_mu = np.asarray(tree["opt_state"].mu)

    # 2-device restore: "model" axis is gone -> w comes back P("data").
    mesh2 = _mesh(2, ("data",))
    out2 = restore_sharded(path, mesh=mesh2)
    w2 = out2["params"]["w"]
    assert w2.sharding.mesh.devices.size == 2
    assert w2.sharding.spec == P("data")
    assert np.array_equal(np.asarray(w2), want_w)
    assert out2["opt_state"].mu.sharding.spec == P("data")
    assert np.array_equal(np.asarray(out2["opt_state"].mu), want_mu)
    assert np.array_equal(np.asarray(out2["params"]["b"]),
                          np.asarray(tree["params"]["b"]))
    assert out2["step"] == 42

    # 1-device restore: every axis drops -> fully replicated.
    mesh1 = _mesh(1, ("data",))
    out1 = restore_sharded(path, mesh=mesh1)
    assert out1["params"]["w"].sharding.spec == P()
    assert np.array_equal(np.asarray(out1["params"]["w"]), want_w)
    assert np.array_equal(np.asarray(out1["opt_state"].mu), want_mu)


def test_restore_with_explicit_sharding(tmp_path):
    """shardings= gives the caller full control: a single Sharding
    applies to every leaf regardless of what was saved."""
    mesh4 = _mesh(4, ("data", "model"), (2, 2))
    tree = {"w": jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh4, P("data", "model")))}
    path = str(tmp_path / "ck")
    save_sharded(path, tree)
    mesh2 = _mesh(2, ("x",))
    sh = NamedSharding(mesh2, P(None, "x"))
    out = restore_sharded(path, shardings=sh)
    assert out["w"].sharding.spec == P(None, "x")
    assert np.array_equal(np.asarray(out["w"]), np.asarray(tree["w"]))


def test_uncommitted_directory_never_restores(tmp_path):
    path = str(tmp_path / "torn")
    save_sharded(path, {"x": np.arange(4)}, commit=False)
    assert not is_committed(path)
    assert os.path.isfile(os.path.join(path, "manifest.json"))
    with pytest.raises(FileNotFoundError, match="COMMIT"):
        restore_sharded(path)
    # Explicit override for forensics.
    out = restore_sharded(path, allow_uncommitted=True)
    assert np.array_equal(out["x"], np.arange(4))


# ---------------------------------------------------------------------------
# Async writer
# ---------------------------------------------------------------------------


def test_async_save_overlaps_caller(tmp_path, monkeypatch):
    """save() returns while the write is still in flight (the step loop
    keeps running); wait_until_finished() is the barrier."""
    gate = threading.Event()
    orig = sharded.write_staged

    def gated_write(staged, path, *, commit=True):
        gate.wait(10)
        return orig(staged, path, commit=commit)

    monkeypatch.setattr(sharded, "write_staged", gated_write)
    ckptr = AsyncCheckpointer()
    path = str(tmp_path / "ck")
    h = ckptr.save(path, {"x": np.arange(8)}, step=1)
    # Caller is back while the writer is gated: overlap proven.
    assert not h.done()
    assert not h.committed()
    assert ckptr.in_flight is h
    gate.set()
    ckptr.wait_until_finished()
    assert h.done() and h.committed()
    assert ckptr.in_flight is None
    assert h.wait(0) == path


def test_async_save_forced_join_one_in_flight(tmp_path, monkeypatch):
    """The next save() force-joins the previous write — at most one
    checkpoint is ever in flight."""
    orig = sharded.write_staged

    def slow_write(staged, path, *, commit=True):
        time.sleep(0.3)
        return orig(staged, path, commit=commit)

    monkeypatch.setattr(sharded, "write_staged", slow_write)
    ckptr = AsyncCheckpointer()
    h1 = ckptr.save(str(tmp_path / "ck1"), {"x": np.arange(4)}, step=1)
    assert not h1.done()
    h2 = ckptr.save(str(tmp_path / "ck2"), {"x": np.arange(4)}, step=2)
    # save() only returned after joining h1's writer.
    assert h1.done() and h1.committed()
    h2.wait(10)
    assert h2.committed()


def test_async_write_error_surfaces_at_barrier(tmp_path, monkeypatch):
    def broken_write(staged, path, *, commit=True):
        raise OSError("disk on fire")

    monkeypatch.setattr(sharded, "write_staged", broken_write)
    ckptr = AsyncCheckpointer()
    h = ckptr.save(str(tmp_path / "ck"), {"x": np.arange(4)}, step=1)
    with pytest.raises(CheckpointWriteError):
        ckptr.wait_until_finished()
    assert h.done() and not h.committed()
    # The error is raised once; the writer is usable again after.
    monkeypatch.setattr(sharded, "write_staged", sharded.write_staged)
    monkeypatch.undo()
    h2 = ckptr.save(str(tmp_path / "ck2"), {"x": np.arange(4)}, step=2,
                    sync=True)
    assert h2.committed()


def test_save_handle_pickles_light(tmp_path):
    """A handle crosses process boundaries as (directory, step); on the
    far side committed() reads the COMMIT marker, not the origin thread."""
    ckptr = AsyncCheckpointer()
    path = str(tmp_path / "ck")
    h = ckptr.save(path, {"x": np.arange(4)}, step=9, sync=True)
    remote = pickle.loads(pickle.dumps(h))
    assert isinstance(remote, SaveHandle)
    assert remote.directory == path and remote.step == 9
    assert remote.done() and remote.committed()
    # A handle to a torn save reports not-committed on the far side.
    torn = str(tmp_path / "torn")
    save_sharded(torn, {"x": np.arange(2)}, commit=False)
    remote2 = pickle.loads(pickle.dumps(SaveHandle(torn, 1)))
    assert not remote2.committed()


# ---------------------------------------------------------------------------
# CheckpointManager: layout, retention, GC
# ---------------------------------------------------------------------------


def test_manager_keep_last_k(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_k=2)
    for step in range(5):
        mgr.save(step, {"x": np.full((4,), step)}, sync=True)
    assert mgr.steps() == [3, 4]
    assert mgr.latest_step() == 4
    out = mgr.restore_latest()
    assert np.array_equal(out["x"], np.full((4,), 4))
    # The evicted directories are really gone.
    assert sorted(os.listdir(tmp_path)) == [
        "checkpoint_000003", "checkpoint_000004"]


def test_manager_keep_best_by_metric(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_best_k=2,
                            best_metric="acc", best_mode="max")
    accs = {0: 0.1, 1: 0.9, 2: 0.5, 3: 0.8, 4: 0.2}
    for step, acc in accs.items():
        mgr.save(step, {"x": np.full((2,), step)}, metrics={"acc": acc},
                 sync=True)
    # Best two by acc (steps 1, 3) plus the latest (4) survive.
    assert mgr.steps() == [1, 3, 4]

    # keep-best survives a restart: a FRESH manager reads metrics back
    # from the manifests, not from in-memory state.
    mgr2 = CheckpointManager(str(tmp_path), keep_best_k=2,
                             best_metric="acc", best_mode="max")
    assert mgr2.metrics_for(1) == {"acc": 0.9}
    mgr2.save(5, {"x": np.full((2,), 5)}, metrics={"acc": 0.0}, sync=True)
    assert mgr2.steps() == [1, 3, 5]


def test_manager_gc_torn_dirs_and_latest_skips_them(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_id="i0")
    mgr.save(1, {"x": np.arange(3)}, sync=True)
    # A torn save at step 2 (crash before COMMIT) ...
    save_sharded(mgr.step_dir(2), {"x": np.arange(3)}, save_id="i0",
                 commit=False)
    assert mgr.latest_step() == 1          # ... is invisible
    out = mgr.restore_latest()
    assert np.array_equal(out["x"], np.arange(3))
    # A torn dir AHEAD of every committed step is preserved (it may be a
    # peer's in-flight save); one at or behind the frontier is GC'd.
    removed = mgr.gc()
    assert removed == []
    mgr.save(3, {"x": np.arange(3)}, sync=True)
    assert not os.path.isdir(mgr.step_dir(2))
    assert mgr.steps() == [1, 3]


def test_manager_async_handles_and_barrier(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_k=1)
    handles = [mgr.save(step, {"x": np.full((8,), step)})
               for step in range(3)]
    mgr.wait_until_finished()
    assert all(h.committed() or not os.path.isdir(h.directory)
               for h in handles)
    assert mgr.steps() == [2]              # retention ran at the barrier
    assert np.array_equal(mgr.restore_latest()["x"], np.full((8,), 2))


# ---------------------------------------------------------------------------
# air.Checkpoint interop + tmp-dir lifecycle
# ---------------------------------------------------------------------------


def test_air_checkpoint_sharded_interop(tmp_path):
    mesh = _mesh(2)
    tree = {"w": jax.device_put(np.arange(8, dtype=np.float32),
                                NamedSharding(mesh, P("data"))),
            "step": 3}
    path = str(tmp_path / "ck")
    save_sharded(path, tree)

    ckpt = Checkpoint.from_sharded_dir(path)
    assert ckpt.is_sharded
    assert ckpt.to_dict()["step"] == 3
    assert np.array_equal(ckpt.to_dict()["w"], np.arange(8))
    # Elastic path through the air layer too.
    out = ckpt.to_pytree(mesh=_mesh(1))
    assert np.array_equal(np.asarray(out["w"]), np.arange(8))

    # Pickling ships the path, never a packed byte blob.
    clone = pickle.loads(pickle.dumps(ckpt))
    assert clone._dir == path and clone.is_sharded

    # A torn directory is rejected at construction.
    torn = str(tmp_path / "torn")
    save_sharded(torn, {"x": np.arange(2)}, commit=False)
    with pytest.raises(ValueError, match="COMMIT"):
        Checkpoint.from_sharded_dir(torn)


def test_checkpoint_tmp_registry_and_cleanup(tmp_path):
    """Satellite: to_directory(None) registers its tmp dir; delete()
    reclaims one checkpoint, cleanup_tmp() sweeps the rest."""
    air_checkpoint.cleanup_tmp()   # start from a clean registry
    a = Checkpoint.from_dict({"x": 1})
    b = Checkpoint.from_dict({"y": 2})
    pa, pb = a.to_directory(), b.to_directory()
    assert os.path.isdir(pa) and os.path.isdir(pb)
    assert Checkpoint.from_directory(pa).to_dict()["x"] == 1

    a.delete()
    assert not os.path.exists(pa)
    assert os.path.isdir(pb)               # delete() is per-checkpoint
    assert air_checkpoint.cleanup_tmp() == 1
    assert not os.path.exists(pb)
    assert air_checkpoint.cleanup_tmp() == 0


# ---------------------------------------------------------------------------
# Trainer e2e: workers report async SaveHandles
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_trainer_worker_async_sharded_checkpoints(cluster, tmp_path):
    """The full wiring: a worker saves sharded checkpoints through
    session.get_checkpoint_manager(), reports the async handle, the
    driver tracks retention, and Result.checkpoint restores."""

    def loop(config):
        import numpy as np
        from ray_tpu.train import session

        mgr = session.get_checkpoint_manager()
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.to_dict()["step"]) + 1
        for step in range(start, 4):
            state = {"w": np.full((8,), float(step)), "step": step}
            handle = mgr.save(step, state, metrics={"loss": 1.0 / (step + 1)})
            session.report({"step": step}, checkpoint=handle)

    run = RunConfig(name="sharded_run", storage_path=str(tmp_path),
                    checkpoint_config=CheckpointConfig(num_to_keep=2))
    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1), run_config=run)
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    assert result.checkpoint is not None and result.checkpoint.is_sharded
    final = result.checkpoint.to_dict()
    assert final["step"] == 3
    assert np.array_equal(final["w"], np.full((8,), 3.0))
    # Retention (num_to_keep=2) applied under storage_path/name.
    root = tmp_path / "sharded_run"
    kept = sorted(p.name for p in root.iterdir())
    assert kept == ["checkpoint_000002", "checkpoint_000003"]
    assert all(is_committed(str(root / p)) for p in kept)

    # Second run resumes from storage via resume_from_checkpoint="latest".
    trainer2 = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1), run_config=run,
        resume_from_checkpoint="latest")
    result2 = trainer2.fit()
    assert result2.error is None
    assert result2.metrics_history == []   # start=4: nothing left


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v", "-x"]))
