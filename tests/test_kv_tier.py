"""Disaggregated serving / tiered KV cache tests (serve/kv_tier).

Covers the three planes of the subsystem without a cluster:

- KVBlockCodec wire format: bit-exact round-trips, garbage rejection.
- KVTierCache lifecycle: seal -> spill -> restore -> adopt bit-exact,
  LRU cascade host -> store -> dropped, counters.
- Allocator conservation under spill pressure (free + evictable + live
  always partitions the pool).
- Prefill->decode handoff: export/import token-exactness (greedy and
  seeded) vs a monolithic engine.
- Router scoring: the `_chain_hashes` copy pinned against the cache's
  `chain_hashes`, prefix-summary staleness fallback, and the DRAINING
  filter in `_pick_replica`.

Cluster-level chaos coverage (prefill/decode replica kills) lives in
test_fault_tolerance.py; end-to-end perf in bench_disagg.py.
"""

import numpy as np
import pytest

from ray_tpu.inference import InferenceEngine
from ray_tpu.inference.kv_cache import PagedKVCache, chain_hashes
from ray_tpu.serve.kv_tier import KVBlockCodec, KVCodecError, KVTierCache


# ---------------------------------------------------------------------------
# Codec
# ---------------------------------------------------------------------------

def _fake_payload(n_blocks=3, block_size=4, layers=2, heads=2, dim=4,
                  seed=0):
    rng = np.random.default_rng(seed)
    shape = (layers, n_blocks, block_size, heads, dim)
    return {
        "v": 1,
        "block_size": block_size,
        "chain": [[int(t) for t in rng.integers(0, 100, block_size)]
                  for _ in range(n_blocks)],
        "k": rng.standard_normal(shape).astype(np.float32),
        "v_pool": rng.standard_normal(shape).astype(np.float32),
    }


def test_codec_roundtrip_bit_exact():
    payload = _fake_payload()
    out = KVBlockCodec.decode(KVBlockCodec.encode(payload))
    assert out["block_size"] == payload["block_size"]
    assert out["chain"] == payload["chain"]
    for key in ("k", "v_pool"):
        assert out[key].dtype == payload[key].dtype
        np.testing.assert_array_equal(out[key], payload[key])


def test_codec_rejects_garbage():
    with pytest.raises(KVCodecError, match="v1 payload"):
        KVBlockCodec.encode({"v": 2})
    with pytest.raises(KVCodecError, match="bytes"):
        KVBlockCodec.decode(12345)
    with pytest.raises(KVCodecError, match="magic"):
        KVBlockCodec.decode(b"NOPE" + b"x" * 64)
    blob = KVBlockCodec.encode(_fake_payload())
    with pytest.raises(KVCodecError, match="corrupt"):
        KVBlockCodec.decode(blob[:20])
    bad = _fake_payload()
    bad["chain"] = bad["chain"][:-1]          # chain/pool disagreement
    import pickle
    framed = b"KVT1" + pickle.dumps({**bad})
    with pytest.raises(KVCodecError, match="shape mismatch"):
        KVBlockCodec.decode(framed)
    # try_decode: bad frame degrades to a miss, never an error.
    assert KVBlockCodec.try_decode(b"garbage") is None
    assert KVBlockCodec.try_decode(blob)["chain"] == \
        _fake_payload()["chain"]


# ---------------------------------------------------------------------------
# Tier cache (no cluster: store tier backs onto spill files)
# ---------------------------------------------------------------------------

def _pair(seed, shape=(1, 4, 2, 2)):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


def test_tier_lru_cascade_and_counters(tmp_path):
    tier = KVTierCache(host_blocks=2, store_blocks=2,
                       spill_dir=str(tmp_path))
    pairs = {i: _pair(i) for i in range(5)}
    keys = [(0, (i,)) for i in range(5)]
    for i, key in enumerate(keys):
        tier.put(key, *pairs[i])
    # 5 spilled, host holds the 2 newest, store the 2 demoted before
    # them, and the oldest fell off the end.
    assert tier.counters["kv_tier_spilled_blocks"] == 5
    assert tier.counters["kv_tier_dropped_blocks"] == 1
    assert len(tier) == 4
    assert not tier.contains(keys[0])
    # Restores are bit-exact from either tier (and consume the entry).
    for i in (1, 2):            # store tier (via spill file)
        k, v = tier.pop(keys[i])
        np.testing.assert_array_equal(k, pairs[i][0])
        np.testing.assert_array_equal(v, pairs[i][1])
    for i in (3, 4):            # host tier
        k, v = tier.pop(keys[i])
        np.testing.assert_array_equal(k, pairs[i][0])
    assert tier.counters["kv_tier_restored_blocks"] == 4
    assert len(tier) == 0
    assert tier.pop(keys[0]) is None          # aged out == miss


def test_tier_put_dedup_and_discard(tmp_path):
    tier = KVTierCache(host_blocks=4, store_blocks=4,
                       spill_dir=str(tmp_path))
    key = (0, (1, 2))
    tier.put(key, *_pair(0))
    tier.put(key, *_pair(0))                  # dedup: one entry, one count
    assert len(tier) == 1
    assert tier.counters["kv_tier_spilled_blocks"] == 1
    assert tier.summary_hashes() == [hash(key)]
    tier.discard(key)                         # re-sealed on device
    assert len(tier) == 0
    assert tier.counters["kv_tier_dropped_blocks"] == 0


# ---------------------------------------------------------------------------
# Cache-level spill/restore + conservation
# ---------------------------------------------------------------------------

def _conserved(cache):
    a = cache.allocator
    live = sum(1 for r in a._ref if r > 0)
    return len(a._free) + len(a._evictable) + live == a.num_blocks


def test_seal_spill_restore_adopt_bit_exact(tmp_path):
    """The full SPILLED lifecycle on one engine: sealed chains evicted
    under pressure come back from the tier and regenerate the exact
    same tokens."""
    eng = InferenceEngine("gpt", "nano", seed=0, auto_start=False,
                          num_blocks=8, block_size=16)
    tier = KVTierCache(host_blocks=4, store_blocks=8,
                       spill_dir=str(tmp_path))
    eng.cache.attach_tier(tier)

    p1 = list(range(1, 49))
    out1 = eng.generate(p1, 8)
    # Two more 3-block prompts force p1's sealed blocks out of the pool.
    eng.generate(list(range(100, 148)), 8)
    eng.generate(list(range(200, 248)), 8)
    assert tier.counters["kv_tier_spilled_blocks"] > 0
    assert _conserved(eng.cache)

    out1_again = eng.generate(p1, 8)
    assert out1_again == out1
    st = eng.stats()
    assert st["restored_blocks"] > 0
    assert _conserved(eng.cache)


def test_conservation_under_spill_pressure(tmp_path):
    """free + evictable + live partitions the pool after arbitrary
    churn with an attached tier — restores and spills never leak or
    double-count a block."""
    eng = InferenceEngine("gpt", "nano", seed=0, auto_start=False,
                          num_blocks=6, block_size=16, max_lanes=2)
    tier = KVTierCache(host_blocks=2, store_blocks=2,
                       spill_dir=str(tmp_path))
    eng.cache.attach_tier(tier)
    prompts = [list(range(s, s + 33)) for s in (1, 50, 100, 1, 50, 100)]
    for p in prompts:
        eng.generate(p, 4)
        assert _conserved(eng.cache)
    a = eng.cache.allocator
    # Every lane is done: nothing may still be live.
    assert sum(1 for r in a._ref if r > 0) == 0
    assert len(a._free) + len(a._evictable) == a.num_blocks


# ---------------------------------------------------------------------------
# Prefill -> decode handoff (export / codec / import)
# ---------------------------------------------------------------------------

def test_export_import_handoff_token_exact():
    """A prefill engine's sealed chain, shipped through the codec and
    adopted by a decode engine, yields token-exact greedy AND seeded
    sampled output vs a monolithic engine (identical seeded weights)."""
    prefill = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
    prompt = list(range(1, 49))               # (48-1)//16 = 2 sealed blocks

    h = prefill.prefill(prompt)
    assert h.tokens() == []                   # prefill_only: no tokens
    payload = prefill.export_prefix(prompt)
    assert payload is not None and len(payload["chain"]) == 2
    blob = KVBlockCodec.encode(payload)

    for temp, seed in ((0.0, None), (0.8, 7)):
        decode = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
        mono = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
        installed = decode.import_prefix(KVBlockCodec.decode(blob))
        assert installed == 2
        # Idempotent: a failover re-import is a no-op.
        assert decode.import_prefix(KVBlockCodec.decode(blob)) == 0
        got = decode.generate(prompt, 12, temperature=temp, seed=seed)
        ref = mono.generate(prompt, 12, temperature=temp, seed=seed)
        assert got == ref
        assert decode.stats()["imported_blocks"] == 2
        assert decode.stats()["prefix_hit_tokens"] >= 32


def test_install_prefix_refuses_foreign_shape():
    eng = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
    bad = _fake_payload(block_size=16)        # nano: wrong heads/dims
    assert eng.import_prefix(bad) == 0
    bad2 = _fake_payload()                    # wrong block size too
    assert eng.import_prefix(bad2) == 0
    assert eng.stats()["imported_blocks"] == 0


def test_prefix_summary_bounded_and_tiered(tmp_path):
    eng = InferenceEngine("gpt", "nano", seed=0, auto_start=False,
                          num_blocks=8, block_size=16)
    tier = KVTierCache(host_blocks=8, store_blocks=8,
                       spill_dir=str(tmp_path))
    eng.cache.attach_tier(tier)
    for s in (1, 50, 100):
        eng.generate(list(range(s, s + 48)), 8)
    summ = eng.prefix_summary(limit=4)
    assert summ["v"] == 1 and summ["block_size"] == 16
    assert len(summ["hashes"]) <= 4           # bounded, newest last
    full = eng.prefix_summary(limit=256)
    # Spilled chains stay visible to the router via the tier.
    assert len(full["hashes"]) >= summ["indexed_blocks"]


# ---------------------------------------------------------------------------
# Router scoring (no cluster)
# ---------------------------------------------------------------------------

def test_router_chain_hashes_pinned_to_cache():
    """serve._private._chain_hashes is a jax-free copy of
    kv_cache.chain_hashes — the router scores replicas correctly only
    while the two stay identical."""
    from ray_tpu.serve._private import _chain_hashes
    rng = np.random.default_rng(3)
    for bs in (1, 4, 16):
        for n in (0, 1, bs, bs + 1, 5 * bs, 5 * bs + 3):
            tokens = [int(t) for t in rng.integers(0, 512, n)]
            assert _chain_hashes(tokens, bs) == chain_hashes(tokens, bs)
            assert len(chain_hashes(tokens, bs)) == max(0, (n - 1) // bs)


class _FakeActorId:
    def __init__(self, b):
        self._b = b

    def binary(self):
        return self._b


class _FakeReplica:
    def __init__(self, b):
        self._actor_id = _FakeActorId(b)


def _handle(name, replicas, states=None):
    from ray_tpu.serve import _private as sp
    h = sp.DeploymentHandle(name)
    st = h._state
    st.replicas = [_FakeReplica(b) for b in replicas]
    st.max_q = 4
    st.states = dict(states or {})
    return h, st


@pytest.fixture
def _clean_router_states():
    yield
    from ray_tpu.serve import _private as sp
    with sp._router_states_lock:
        sp._router_states.clear()


def test_pick_replica_filters_draining(_clean_router_states):
    """The _pick_replica DRAINING fix: drained replicas never attract
    new traffic, even when idle (the old sampler only noticed them at
    the in-flight probe)."""
    from ray_tpu.serve._private import REPLICA_DRAINING, REPLICA_RUNNING
    h, st = _handle("kvt_drain", [b"a", b"b"],
                    {b"a": REPLICA_RUNNING, b"b": REPLICA_DRAINING})
    for _ in range(20):
        replica, key = h._pick_replica()
        assert key == b"a"
        h._done(key)
    # All-DRAINING (stale/partial table) must not brick routing.
    st.states = {b"a": REPLICA_DRAINING, b"b": REPLICA_DRAINING}
    assert h._pick_replica() is not None


def test_pick_replica_prefers_deepest_prefix(_clean_router_states):
    """`prefer` stable-sorts deepest-cached-prefix first; p2c order is
    exactly the tie-break."""
    h, st = _handle("kvt_prefer", [b"a", b"b", b"c"])
    picks = set()
    for _ in range(10):
        replica, key = h._pick_replica({b"b": 3, b"c": 1})
        picks.add(key)
        h._done(key)
    assert picks == {b"b"}
    # Saturate the preferred replica: the next-best candidate wins.
    st.in_flight[b"b"] = st.max_q
    replica, key = h._pick_replica({b"b": 3, b"c": 1})
    assert key == b"c"


def test_prefix_order_staleness_fallback(monkeypatch,
                                         _clean_router_states):
    """Summaries older than serve_prefix_staleness_s never score: a
    dead/redeployed replica's stale summary cannot attract traffic, and
    with no fresh summaries the router falls back to pure p2c (None)."""
    import time as _time
    from ray_tpu._private.config import GLOBAL_CONFIG
    monkeypatch.setenv("RAY_TPU_SERVE_PREFIX_ROUTING", "1")
    monkeypatch.setenv("RAY_TPU_SERVE_PREFIX_STALENESS_S", "5.0")
    GLOBAL_CONFIG.invalidate_cache()
    try:
        h, st = _handle("kvt_stale", [b"a", b"b"])
        prompt = list(range(1, 49))
        hashes = set(chain_hashes(prompt, 16))
        now = _time.monotonic()
        st.prefix = {
            b"a": {"hashes": hashes, "block_size": 16, "ts": now},
            b"b": {"hashes": hashes, "block_size": 16, "ts": now - 60},
        }
        scores = h._prefix_order((prompt,), {})
        assert scores == {b"a": 2}            # stale b never scores
        # Every summary stale -> None -> pure p2c fallback.
        st.prefix[b"a"]["ts"] = now - 60
        assert h._prefix_order((prompt,), {}) is None
        # Non-token prompts never score (text requests use p2c).
        assert h._prefix_order(("hello",), {}) is None
        assert h._prefix_order((), {}) is None
    finally:
        GLOBAL_CONFIG.invalidate_cache()
