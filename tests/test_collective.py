"""Actor-group collective tests (reference:
python/ray/util/collective/tests/ — allreduce/allgather/broadcast/
send-recv across an actor fleet; here over the objstore host plane)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=512 << 20)
    yield info
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=1)
class Member:
    def __init__(self, world, rank, group):
        from ray_tpu.util import collective
        self.c = collective
        self.rank = rank
        self.group = group
        self.c.init_collective_group(world, rank, group_name=group)

    def do_allreduce(self, op="SUM"):
        return self.c.allreduce(np.full(4, self.rank + 1.0), "g", op=op)

    def do_allgather(self):
        return self.c.allgather(np.full(2, float(self.rank)), "g")

    def do_reducescatter(self):
        return self.c.reducescatter(np.arange(8.0) + self.rank, "g")

    def do_broadcast(self):
        return self.c.broadcast(
            np.full(3, 42.0 if self.rank == 1 else -1.0), src_rank=1,
            group_name="g")

    def do_sendrecv(self):
        if self.rank == 0:
            self.c.send(np.full(2, 7.0), dest_rank=1, group_name="g")
            return None
        if self.rank == 1:
            return self.c.recv(src_rank=0, group_name="g")
        return None

    def do_barrier(self):
        self.c.barrier("g")
        return self.rank

    def do_big_reducescatter(self, n):
        # Identifiable per-rank contribution: sum = world*arange(n)+const.
        arr = np.arange(float(n)) + self.rank
        return self.c.reducescatter(arr, self.group)

    def do_big_allgather(self, n):
        return self.c.allgather(np.arange(float(n)) + self.rank, self.group)

    def do_big_broadcast(self, n):
        return self.c.broadcast(np.arange(float(n)) + self.rank,
                                src_rank=1, group_name=self.group)

    def do_big_sendrecv(self, n):
        if self.rank == 0:
            self.c.send(np.arange(float(n)) * 2, dest_rank=2,
                        group_name=self.group)
            return None
        if self.rank == 2:
            return self.c.recv(src_rank=0, group_name=self.group)
        return None

    def do_big_allreduce(self, nbytes):
        arr = np.full(nbytes // 8, self.rank + 1.0)
        import time
        t0 = time.perf_counter()
        out = self.c.allreduce(arr, self.group)
        dt = time.perf_counter() - t0
        return float(out[0]), float(out[-1]), dt

    def coordinator_payload_bytes(self):
        import ray_tpu as rt
        return rt.get(
            self.c._groups[self.group].coord.payload_bytes_through.remote())


def test_collective_ops_across_actor_fleet(cluster):
    world = 4
    members = [Member.remote(world, r, "g") for r in range(world)]

    # allreduce SUM: 1+2+3+4 = 10 in every rank
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members],
                       timeout=120)
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 10.0))

    # allreduce MAX
    outs = ray_tpu.get([m.do_allreduce.remote("MAX") for m in members],
                       timeout=120)
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 4.0))

    # allgather: every rank sees [0,0],[1,1],[2,2],[3,3]
    outs = ray_tpu.get([m.do_allgather.remote() for m in members],
                       timeout=120)
    for out in outs:
        assert [list(x) for x in out] == [[r, r] for r in range(world)]

    # reducescatter SUM of (arange(8)+r): total = 4*arange(8)+6, rank r
    # gets rows [2r, 2r+2)
    outs = ray_tpu.get([m.do_reducescatter.remote() for m in members],
                       timeout=120)
    total = 4 * np.arange(8.0) + 6
    for r, out in enumerate(outs):
        np.testing.assert_array_equal(out, total[2 * r: 2 * r + 2])

    # broadcast from rank 1
    outs = ray_tpu.get([m.do_broadcast.remote() for m in members],
                       timeout=120)
    for out in outs:
        np.testing.assert_array_equal(out, np.full(3, 42.0))

    # p2p send/recv
    outs = ray_tpu.get([m.do_sendrecv.remote() for m in members],
                       timeout=120)
    np.testing.assert_array_equal(outs[1], np.full(2, 7.0))

    # barrier completes for everyone
    assert sorted(ray_tpu.get(
        [m.do_barrier.remote() for m in members], timeout=120)) == [0, 1, 2, 3]

    for m in members:
        ray_tpu.kill(m)


# `slow`: ~43s = 5% of the tier-1 budget spent memcpying 100MB x 8 ranks
# on one host; the ring path + refs-only-coordinator invariant stay
# tier-1-covered by the >=64KB reducescatter/allgather tests below.
@pytest.mark.slow
def test_ring_allreduce_100mb_world8(cluster):
    """Bulk collectives are ring-based over direct store-to-store object
    transfers; the coordinator relays only refs (VERDICT r2 item 4: 100MB
    allreduce at world=8 with bytes-through-coordinator ~ 0)."""
    world = 8
    members = [Member.remote(world, r, "gbig") for r in range(world)]
    # patch group name used inside the actor helpers
    outs = ray_tpu.get(
        [m.do_big_allreduce.remote(100 << 20) for m in members],
        timeout=600)
    expect = sum(range(1, world + 1))  # 36
    for first, last, _dt in outs:
        assert first == expect and last == expect
    secs = max(dt for _, _, dt in outs)
    print(f"ring allreduce 100MB world=8: {100 / secs:.0f} MB/s/rank")
    # Coordinator never saw payload bytes (refs only).
    bytes_through = ray_tpu.get(members[0].coordinator_payload_bytes
                                .remote())
    assert bytes_through == 0
    for m in members:
        ray_tpu.kill(m)


def test_ring_reducescatter_large_segment_identity(cluster):
    """>=64KB payloads take the ring path; rank r must receive reduced
    partition r (ADVICE r3: the ring used to hand rank r its right
    neighbour's partition once payloads crossed the small threshold)."""
    world = 4
    n = 32768  # 256 KB float64, well over the 64 KB small-path cutoff
    members = [Member.remote(world, r, "grs") for r in range(world)]
    outs = ray_tpu.get(
        [m.do_big_reducescatter.remote(n) for m in members], timeout=300)
    total = world * np.arange(float(n)) + sum(range(world))
    expected_segs = np.array_split(total, world)
    for r, out in enumerate(outs):
        np.testing.assert_array_equal(out, expected_segs[r])
    for m in members:
        ray_tpu.kill(m)


def test_big_allgather_broadcast_sendrecv(cluster):
    """Every bulk (>=64KB, ring/ref) path moves correct data: a bare
    ObjectRef argument used to be RESOLVED at the coordinator (reference
    arg semantics), shipping whole payloads through it — allgather got
    arrays instead of refs, recv skipped its ack so big sends deadlocked."""
    world = 4
    n = 32768  # 256 KB float64
    members = [Member.remote(world, r, "gbulk") for r in range(world)]
    outs = ray_tpu.get(
        [m.do_big_allgather.remote(n) for m in members], timeout=300)
    for out in outs:
        assert len(out) == world
        for r in range(world):
            np.testing.assert_array_equal(out[r],
                                          np.arange(float(n)) + r)
    outs = ray_tpu.get(
        [m.do_big_broadcast.remote(n) for m in members], timeout=300)
    for out in outs:
        np.testing.assert_array_equal(out, np.arange(float(n)) + 1)
    outs = ray_tpu.get(
        [m.do_big_sendrecv.remote(n) for m in members], timeout=300)
    np.testing.assert_array_equal(outs[2], np.arange(float(n)) * 2)
    # Bulk payloads never ride the coordinator.
    assert ray_tpu.get(
        members[0].coordinator_payload_bytes.remote()) == 0
    for m in members:
        ray_tpu.kill(m)


def test_collective_requires_init(cluster):
    from ray_tpu.util import collective
    with pytest.raises(RuntimeError):
        collective.allreduce(np.ones(2), group_name="nope")
