"""Inference engine tests: paged KV cache invariants, content-addressed
prefix caching (seal/match/adopt/evict + token-exactness vs a cold
engine), cached-decode vs full-forward logits equivalence (GPT +
Llama/GQA), the paged attention kernel against its dense reference,
continuous-batching lane admission and pool-exhaustion FIFO, in-step
sampling determinism, and end-to-end streaming generation through
serve."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.inference import BlockAllocator, InferenceEngine, PagedKVCache
from ray_tpu.models import gpt, llama
from ray_tpu.ops import paged_attention_reference, paged_decode_attention, \
    paged_kv_update


# ---------------------------------------------------------------------------
# Block allocator / cache invariants
# ---------------------------------------------------------------------------

def test_allocator_alloc_free_reuse():
    a = BlockAllocator(4)
    b1 = a.alloc(3)
    assert a.num_free == 1
    assert len(set(b1)) == 3
    a.free(b1[:2])
    assert a.num_free == 3
    # LIFO: the most recently freed block comes back first.
    b2 = a.alloc(1)
    assert b2[0] == b1[1]
    assert a.can_alloc(2) and not a.can_alloc(3)


def test_allocator_exhaustion_and_double_free():
    a = BlockAllocator(2)
    blocks = a.alloc(2)
    with pytest.raises(RuntimeError, match="exhausted"):
        a.alloc(1)
    a.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        a.free(blocks)


def test_cache_lane_lifecycle():
    cache = PagedKVCache(n_layers=1, kv_heads=2, head_dim=4, num_blocks=6,
                         block_size=4, max_lanes=2, max_seq_len=24)
    cache.alloc_lane(0, prompt_len=9)          # 3 blocks
    assert len(cache.lane_blocks(0)) == 3
    assert cache.allocator.num_free == 3
    with pytest.raises(ValueError, match="already allocated"):
        cache.alloc_lane(0, prompt_len=1)
    # Growth across a block boundary claims exactly one more block.
    cache.ensure_capacity(0, 12)
    assert len(cache.lane_blocks(0)) == 3
    cache.ensure_capacity(0, 13)
    assert len(cache.lane_blocks(0)) == 4
    # Freeing returns every block; the table is reusable by a new lane.
    freed = cache.lane_blocks(0)
    cache.free_lane(0)
    assert cache.allocator.num_free == 6
    cache.alloc_lane(1, prompt_len=16)
    assert set(cache.lane_blocks(1)) & set(freed)  # blocks are recycled
    with pytest.raises(RuntimeError, match="max_seq_len"):
        cache.ensure_capacity(1, 25)


def test_cache_admission_control():
    cache = PagedKVCache(n_layers=1, kv_heads=1, head_dim=4, num_blocks=4,
                         block_size=4, max_lanes=4, max_seq_len=16)
    assert cache.can_admit(16)
    cache.alloc_lane(0, prompt_len=12)         # 3 of 4 blocks
    assert cache.can_admit(4) and not cache.can_admit(5)


def test_allocator_refcount_and_lru_eviction():
    evicted = []
    a = BlockAllocator(3, on_evict=evicted.append)
    b = a.alloc(2)
    a.mark_cached(b[0])
    a.mark_cached(b[1])
    a.free(b)                       # cached blocks park evictable, not free
    assert a.num_free == 3          # evictable still counts as capacity
    assert a.is_evictable(b[0]) and a.is_evictable(b[1])
    a.incref(b[1])                  # prefix reuse revives an evictable block
    assert not a.is_evictable(b[1]) and a.refcount(b[1]) == 1
    # Allocating past the plain-free supply evicts LRU-first (b[0]) and
    # fires the index-drop hook; the live share of b[1] is untouchable.
    got = a.alloc(2)
    assert evicted == [b[0]]
    assert a.evictions == 1
    assert b[1] not in got
    a.free([b[1]] + got)
    assert a.num_free == 3


# ---------------------------------------------------------------------------
# Prefix cache: seal / match / adopt / evict
# ---------------------------------------------------------------------------

def test_prefix_cache_seal_match_adopt():
    cache = PagedKVCache(n_layers=1, kv_heads=1, head_dim=4, num_blocks=8,
                         block_size=4, max_lanes=2, max_seq_len=32)
    toks = list(range(1, 13))                    # 12 tokens = 3 full blocks
    cache.alloc_lane(0, 12)
    cache.seq_lens[0] = 12
    cache.seal_full_blocks(0, toks)
    assert cache.num_indexed_blocks == 3
    # The match is capped so at least one prompt token always prefills
    # (its logits seed the first sampled token).
    assert len(cache.match_prefix(toks)) == 2
    assert cache.match_prefix(toks + [99]) == cache.lane_blocks(0)[:3]
    # A diverging block breaks the chain at the divergence point.
    assert len(cache.match_prefix(toks[:4] + [77] + toks[5:] + [99])) == 1
    # Adoption takes refcounted shares of blocks a LIVE lane still owns —
    # mid-flight sharing, no copy.
    reused = cache.adopt_prefix(1, toks + [99, 98])
    assert reused == 12
    shared = cache.lane_blocks(0)[:3]
    assert cache.lane_blocks(1)[:3] == shared
    assert all(cache.allocator.refcount(b) == 2 for b in shared)
    cache.free_lane(0)
    assert all(cache.allocator.refcount(b) == 1 for b in shared)
    cache.free_lane(1)
    # Finished sequences leave sealed blocks indexed at refcount 0: still
    # counted free, still matchable.
    assert cache.allocator.num_free == 8
    assert cache.num_indexed_blocks == 3
    assert len(cache.match_prefix(toks + [99])) == 3


def test_prefix_cache_lru_eviction_under_pressure():
    cache = PagedKVCache(n_layers=1, kv_heads=1, head_dim=4, num_blocks=4,
                         block_size=4, max_lanes=2, max_seq_len=16)
    toks = list(range(1, 9))                     # 8 tokens = 2 blocks
    cache.alloc_lane(0, 8)
    cache.seq_lens[0] = 8
    cache.seal_full_blocks(0, toks)
    cache.free_lane(0)
    assert cache.num_indexed_blocks == 2
    assert cache.allocator.num_free == 4
    # A 16-token request wants the whole pool: plain-free blocks first,
    # then the cached pair is reclaimed LRU and drops out of the index.
    cache.alloc_lane(1, 16)
    assert cache.allocator.evictions == 2
    assert cache.num_indexed_blocks == 0
    assert cache.match_prefix(toks + [9]) == []


# ---------------------------------------------------------------------------
# Paged attention: kernel (interpret) vs dense reference
# ---------------------------------------------------------------------------

def test_paged_kv_update_masks_invalid_lanes():
    nb, bs, kh, d = 4, 4, 2, 8
    k_pool = jnp.zeros((nb, bs, kh, d))
    v_pool = jnp.zeros((nb, bs, kh, d))
    k_new = jnp.ones((2, 1, kh, d))
    v_new = jnp.ones((2, 1, kh, d))
    tables = jnp.array([[1, 2], [3, 0]], jnp.int32)
    positions = jnp.array([[0], [5]], jnp.int32)
    valid = jnp.array([[True], [False]])
    k2, v2 = paged_kv_update(k_pool, v_pool, k_new, v_new, tables,
                             positions, valid)
    assert float(k2[1, 0].sum()) == kh * d      # lane 0 wrote block 1 slot 0
    # The invalid lane wrote nowhere — pool otherwise untouched.
    assert float(k2.sum()) == kh * d
    assert float(v2.sum()) == kh * d


@pytest.mark.parametrize("q_per_kv", [1, 4])
def test_paged_decode_kernel_matches_reference(q_per_kv):
    rng = np.random.default_rng(0)
    b, kh, d, bs, mb = 3, 2, 64, 8, 4
    h = kh * q_per_kv
    nb = 16
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((nb, bs, kh, d)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((nb, bs, kh, d)), jnp.float32)
    tables = jnp.asarray(rng.permutation(nb)[:b * mb].reshape(b, mb),
                         jnp.int32)
    ctx_lens = jnp.asarray([5, 17, 32], jnp.int32)   # partial/multi/full
    out_k = paged_decode_attention(q, k_pool, v_pool, tables, ctx_lens,
                                   use_kernel=True, interpret=True)
    out_ref = paged_attention_reference(
        q[:, None], k_pool, v_pool, tables, ctx_lens,
        (ctx_lens - 1)[:, None])[:, 0]
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# Cached decode == full forward (the correctness core of the engine)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_cached_logits_match_full_forward(family):
    model = gpt if family == "gpt" else llama
    config = model.CONFIGS["nano" if family == "gpt" else "llama-tiny"]
    params = model.init_params(config, jax.random.key(1))
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, config.vocab_size, size=21).tolist()
    prefill = 6

    full = model.forward(params, jnp.asarray([tokens], jnp.int32), config)
    if isinstance(full, tuple):                 # gpt returns (logits, aux)
        full = full[0]
    full = np.asarray(full[0], np.float32)      # [n, vocab]

    n = len(tokens)
    block_size = 8
    cache = PagedKVCache.for_model(
        model, config, num_blocks=-(-n // block_size) + 1,
        block_size=block_size, max_lanes=1, max_seq_len=config.max_seq_len)
    cache.alloc_lane(0, n)

    got = {}

    def run(chunk, start):
        t = len(chunk)
        x, k, v = model.forward_cached(
            params, jnp.asarray([chunk], jnp.int32),
            jnp.asarray([np.arange(start, start + t)], jnp.int32),
            jnp.ones((1, t), bool), cache.k, cache.v,
            cache.device_tables(), jnp.asarray([start + t], jnp.int32),
            config)
        cache.update_pools(k, v)
        got[start + t - 1] = np.asarray(
            model.lm_head(params, x[:, -1], config)[0], np.float32)

    run(tokens[:prefill], 0)                    # chunked prefill
    for i in range(prefill, n):                 # then position > 0 decode
        run(tokens[i:i + 1], i)

    for pos, logits in got.items():
        np.testing.assert_allclose(logits, full[pos], atol=2e-4, rtol=2e-4,
                                   err_msg=f"{family} position {pos}")


# ---------------------------------------------------------------------------
# Continuous batching: lane admission mid-flight
# ---------------------------------------------------------------------------

def test_engine_admits_waiting_request_mid_flight():
    eng = InferenceEngine("gpt", "nano", max_lanes=2, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=0)
    h1 = eng.submit([3, 1, 4], max_new_tokens=3)
    h2 = eng.submit([2, 7, 1], max_new_tokens=12)
    h3 = eng.submit([5, 9, 2], max_new_tokens=3)
    assert eng.num_waiting == 3

    saw_mid_flight_admission = False
    while eng.step():
        # The third request must enter lane 0/1 while the long request
        # is still mid-generation — no batch barrier.
        if eng.num_waiting == 0 and eng.num_active == 2 and \
                h1.finish_reason == "length" and \
                h2.finish_reason is None:
            saw_mid_flight_admission = True
    assert saw_mid_flight_admission
    assert len(h1.tokens()) == 3
    assert len(h2.tokens()) == 12
    assert len(h3.tokens()) == 3
    # Everything was freed on finish.
    assert eng.num_active == 0
    assert eng.cache.allocator.num_free == eng.cache.allocator.num_blocks

    # Batched-greedy output equals one-at-a-time generation.
    solo = InferenceEngine("gpt", "nano", params=eng.params, max_lanes=1,
                           block_size=8, prefill_chunk=4, auto_start=False)
    eng2 = InferenceEngine("gpt", "nano", params=eng.params, max_lanes=2,
                           block_size=8, prefill_chunk=4, auto_start=False)
    hs = [eng2.submit(p, max_new_tokens=5)
          for p in ([3, 1, 4], [2, 7, 1], [5, 9, 2])]
    while eng2.step():
        pass
    batched = [h.tokens() for h in hs]
    for prompt, got in zip(([3, 1, 4], [2, 7, 1], [5, 9, 2]), batched):
        assert got == solo.generate(prompt, max_new_tokens=5)


def test_engine_temperature_sampling_and_eos():
    eng = InferenceEngine("gpt", "nano", max_lanes=1, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=7)
    toks = eng.generate([1, 2, 3], max_new_tokens=50, temperature=1.0)
    assert 0 < len(toks) <= 50
    assert all(0 <= t < eng.config.vocab_size for t in toks)
    # eos_id cuts generation short the moment it is sampled.
    greedy = eng.generate([1, 2, 3], max_new_tokens=8)
    if len(greedy) > 1:
        h = eng.submit([1, 2, 3], max_new_tokens=8, eos_id=greedy[0])
        while eng.step():
            pass
        assert h.tokens() == greedy[:1]
        assert h.finish_reason == "eos"


# ---------------------------------------------------------------------------
# Prefix reuse: token-exactness vs a cold engine
# ---------------------------------------------------------------------------

def test_prefix_reuse_token_exact_vs_cold():
    warm = InferenceEngine("gpt", "nano", max_lanes=2, block_size=8,
                           prefill_chunk=8, auto_start=False, seed=0)
    cold = InferenceEngine("gpt", "nano", params=warm.params, max_lanes=2,
                           block_size=8, prefill_chunk=8, auto_start=False,
                           seed=0, prefix_cache=False)
    prefix = list(range(1, 25))                  # 24 shared tokens
    p1, p2 = prefix + [30, 31], prefix + [40, 41, 42]

    a1 = warm.generate(p1, max_new_tokens=6)     # seals the prefix
    assert warm.stats()["prefix_hits"] == 0
    a2 = warm.generate(p2, max_new_tokens=6)     # admits via the cache
    assert warm.stats()["prefix_hits"] == 1
    assert warm.stats()["prefix_hit_tokens"] == 24
    # Greedy output with prefix reuse is identical to full prefill.
    assert cold.generate(p1, max_new_tokens=6) == a1
    assert cold.generate(p2, max_new_tokens=6) == a2
    # Seeded sampling too: the PRNG key depends only on (seed, produced).
    s_warm = warm.generate(p2, max_new_tokens=6, temperature=0.9, seed=123)
    s_cold = cold.generate(p2, max_new_tokens=6, temperature=0.9, seed=123)
    assert warm.stats()["prefix_hits"] == 2
    assert s_warm == s_cold


def test_sampled_output_independent_of_batch_composition():
    eng = InferenceEngine("gpt", "nano", max_lanes=4, block_size=8,
                          prefill_chunk=8, auto_start=False, seed=0)
    prompt = [2, 3, 4, 5, 6]
    solo = eng.generate(prompt, max_new_tokens=6, temperature=0.8, seed=99)
    # Same request inside a full, heterogeneous batch (different prompts,
    # temperatures, greedy neighbours) must sample the same tokens.
    h = eng.submit(prompt, max_new_tokens=6, temperature=0.8, seed=99)
    eng.submit([9, 8, 7], max_new_tokens=6, temperature=1.3, seed=5)
    eng.submit([1, 1, 2, 3], max_new_tokens=4)
    eng.submit([4, 4], max_new_tokens=8, temperature=0.4, seed=99)
    while eng.step():
        pass
    assert h.tokens() == solo


# ---------------------------------------------------------------------------
# Admission under pool exhaustion
# ---------------------------------------------------------------------------

def test_admission_fifo_head_not_starved_by_smaller_requests():
    # Pool of 6 blocks x 4 tokens.  r1 fits; r2 (20 tokens = 5 blocks + 1
    # headroom) cannot fit while r1 is live; r3 (1 block + headroom)
    # COULD fit but must wait behind r2 — FIFO admission never starves
    # the head.
    eng = InferenceEngine("gpt", "nano", max_lanes=3, block_size=4,
                          num_blocks=6, max_seq_len=24, prefill_chunk=4,
                          auto_start=False, seed=0)
    h1 = eng.submit(list(range(1, 9)), max_new_tokens=8)
    h2 = eng.submit(list(range(1, 21)), max_new_tokens=2)
    h3 = eng.submit([7, 7, 7, 7], max_new_tokens=2)
    eng.step()
    assert eng.num_active == 1 and eng.num_waiting == 2
    order = []
    while eng.step():
        for h, name in ((h2, "r2"), (h3, "r3")):
            if h.finish_reason and name not in order:
                order.append(name)
    # r2 entered (a lane freed mid-flight was reused) and finished before
    # r3 was admitted.
    assert order == ["r2", "r3"]
    assert len(h1.tokens()) == 8
    assert len(h2.tokens()) == 2
    assert len(h3.tokens()) == 2
    assert eng.cache.allocator.num_free == eng.cache.allocator.num_blocks


# ---------------------------------------------------------------------------
# Satellites: submit validation, tokens() deadline, no [B, V] transfer
# ---------------------------------------------------------------------------

def test_submit_validates_inputs():
    eng = InferenceEngine("gpt", "nano", max_lanes=1, auto_start=False)
    vocab = eng.config.vocab_size
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([])
    with pytest.raises(ValueError, match="out of range"):
        eng.submit([1, vocab])
    with pytest.raises(ValueError, match="out of range"):
        eng.submit([-1])
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1], max_new_tokens=0)


def test_tokens_timeout_is_overall_deadline():
    from ray_tpu.inference.engine import GenerationHandle, _Request
    req = _Request(rid=1, prompt=[1], max_new_tokens=100)
    h = GenerationHandle(req)

    def feeder():   # a token every 50ms — each gap alone beats 0.4s
        for i in range(100):
            time.sleep(0.05)
            req.out.put(i)

    threading.Thread(target=feeder, daemon=True).start()
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):   # and never queue.Empty
        h.tokens(timeout=0.4)
    # Per-token semantics would stream all 100 tokens (~5s) without
    # raising; the overall deadline fires at ~0.4s.
    assert time.monotonic() - t0 < 2.0


def test_cancel_evicts_lane_and_engine_stays_usable():
    eng = InferenceEngine("gpt", "nano", max_lanes=2, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=0)
    h = eng.submit([1, 2, 3], max_new_tokens=1000)
    eng.step()
    assert eng.num_active == 1
    assert h.cancel() is True
    assert h.finish_reason == "cancelled"
    assert h.cancel() is False          # idempotent
    assert eng.num_active == 0
    assert eng.cache.allocator.num_free == eng.cache.allocator.num_blocks
    # The lane is genuinely reusable afterwards.
    assert len(eng.generate([4, 5, 6], max_new_tokens=3)) == 3


def test_tokens_timeout_cancels_upstream():
    """Satellite fix: a client-side tokens() deadline must CANCEL the
    request (dequeue / evict the lane), not leave the engine generating
    for a consumer that already gave up."""
    eng = InferenceEngine("gpt", "nano", max_lanes=1, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=0)
    h = eng.submit([1, 2, 3], max_new_tokens=1000)
    assert eng.num_waiting == 1
    with pytest.raises(TimeoutError):
        h.tokens(timeout=0.1)           # never stepped: still queued
    assert h.finish_reason == "cancelled"
    assert eng.num_waiting == 0 and eng.num_active == 0


def test_request_deadline_evicts_lane():
    eng = InferenceEngine("gpt", "nano", max_lanes=1, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=0)
    h = eng.submit([1, 2, 3], max_new_tokens=100000, deadline_s=0.15)
    while eng.step():
        pass
    assert h.finish_reason == "deadline"
    assert len(h.tokens()) < 100000
    assert eng.num_active == 0
    assert eng.cache.allocator.num_free == eng.cache.allocator.num_blocks


def test_sample_offset_resume_is_seed_consistent():
    """Failover building block: resubmitting with the produced tokens
    appended to the prompt and sample_offset=len(produced) draws the
    SAME per-step sampling keys the original request would have drawn,
    so a resumed sampled stream is token-exact."""
    eng = InferenceEngine("gpt", "nano", max_lanes=2, block_size=8,
                          prefill_chunk=8, auto_start=False, seed=0)
    prompt = [2, 3, 4, 5]
    full = eng.generate(prompt, max_new_tokens=8, temperature=0.9, seed=42)
    if len(full) < 4:
        pytest.skip("sampled run hit max_seq_len too early")
    part = eng.generate(prompt, max_new_tokens=3, temperature=0.9, seed=42)
    assert part == full[:3]
    h = eng.submit(prompt + part, max_new_tokens=len(full) - 3,
                   temperature=0.9, seed=42, sample_offset=3)
    while eng.step():
        pass
    assert h.tokens() == full[3:]


def test_sampled_step_keeps_logits_on_device():
    eng = InferenceEngine("gpt", "nano", max_lanes=2, block_size=8,
                          max_seq_len=32, prefill_chunk=8,
                          auto_start=False, seed=0)
    h = eng.submit([1, 2, 3, 4], max_new_tokens=3, temperature=0.7, seed=1)
    while eng.step():
        pass
    assert len(h.tokens()) == 3
    assert True in eng._step_impls      # the sampling step really ran
    vocab = eng.config.vocab_size
    b = eng.max_lanes
    for t in (1, eng.prefill_chunk):
        for impl in eng._step_impls.values():
            out = jax.eval_shape(
                impl, eng.params, eng.cache.k, eng.cache.v,
                jnp.zeros((b, t), jnp.int32), jnp.zeros((b, t), jnp.int32),
                jnp.zeros((b, t), bool), eng.cache.device_tables(),
                jnp.ones((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.float32), jnp.zeros((b,), jnp.uint32),
                jnp.zeros((b,), jnp.int32))
            next_tok = jax.tree_util.tree_leaves(out)[0]
            assert next_tok.shape == (b,)   # one int per lane comes home
            # No step output carries a vocab-sized dim: sampling happened
            # in-graph and the [B, V] logits never left the device.
            for leaf in jax.tree_util.tree_leaves(out):
                assert vocab not in leaf.shape


# ---------------------------------------------------------------------------
# Serve integration: streaming generation end-to-end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    import ray_tpu
    from ray_tpu import serve
    info = ray_tpu.init(num_cpus=8, object_store_memory=64 << 20)
    serve.start()
    yield info
    serve.shutdown()
    ray_tpu.shutdown()


def test_llm_deployment_streams_tokens(cluster):
    from ray_tpu import serve
    handle = serve.run(serve.LLMDeployment.bind(
        model="gpt", config="nano", max_lanes=4, block_size=8,
        prefill_chunk=4))
    prompt = [3, 14, 15, 9]
    streamed = list(handle.options("generate").stream(
        prompt, max_new_tokens=6))
    assert len(streamed) == 6
    assert all(isinstance(t, int) for t in streamed)
    # Non-streaming call agrees with the streamed tokens (greedy).
    assert handle.remote(prompt, 6).result(timeout=60) == streamed
    stats = handle.stats.remote().result(timeout=60)
    assert stats["active"] == 0 and stats["max_lanes"] == 4
    serve.delete("llm")


def test_llm_replica_metrics_scraped_through_cli_path(cluster):
    from ray_tpu import serve, state
    handle = serve.run(serve.LLMDeployment.bind(
        model="gpt", config="nano", max_lanes=2, block_size=8,
        prefill_chunk=4))
    prompt = list(range(1, 18))
    first = handle.remote(prompt, 4).result(timeout=120)
    second = handle.remote(prompt, 4).result(timeout=120)
    assert first == second
    # The engine lives in a serve replica (a worker process); its
    # counters must reach the node-level scrape `cli metrics` renders —
    # hostd pulls worker registries over the CoreWorker Metrics RPC and
    # merges them into its own snapshot.
    text = state.prometheus_metrics()
    assert "inference_prefix_hit_tokens" in text
    assert "inference_prefix_miss_tokens" in text
    assert "inference_waiting_requests" in text
    stats = handle.stats.remote().result(timeout=60)
    assert stats["prefix_hits"] >= 1        # second request reused blocks
    assert stats["prefix_hit_tokens"] >= 16
    serve.delete("llm")
