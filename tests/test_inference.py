"""Inference engine tests: paged KV cache invariants, cached-decode vs
full-forward logits equivalence (GPT + Llama/GQA), the paged attention
kernel against its dense reference, continuous-batching lane admission,
and end-to-end streaming generation through serve."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.inference import BlockAllocator, InferenceEngine, PagedKVCache
from ray_tpu.models import gpt, llama
from ray_tpu.ops import paged_attention_reference, paged_decode_attention, \
    paged_kv_update


# ---------------------------------------------------------------------------
# Block allocator / cache invariants
# ---------------------------------------------------------------------------

def test_allocator_alloc_free_reuse():
    a = BlockAllocator(4)
    b1 = a.alloc(3)
    assert a.num_free == 1
    assert len(set(b1)) == 3
    a.free(b1[:2])
    assert a.num_free == 3
    # LIFO: the most recently freed block comes back first.
    b2 = a.alloc(1)
    assert b2[0] == b1[1]
    assert a.can_alloc(2) and not a.can_alloc(3)


def test_allocator_exhaustion_and_double_free():
    a = BlockAllocator(2)
    blocks = a.alloc(2)
    with pytest.raises(RuntimeError, match="exhausted"):
        a.alloc(1)
    a.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        a.free(blocks)


def test_cache_lane_lifecycle():
    cache = PagedKVCache(n_layers=1, kv_heads=2, head_dim=4, num_blocks=6,
                         block_size=4, max_lanes=2, max_seq_len=24)
    cache.alloc_lane(0, prompt_len=9)          # 3 blocks
    assert len(cache.lane_blocks(0)) == 3
    assert cache.allocator.num_free == 3
    with pytest.raises(ValueError, match="already allocated"):
        cache.alloc_lane(0, prompt_len=1)
    # Growth across a block boundary claims exactly one more block.
    cache.ensure_capacity(0, 12)
    assert len(cache.lane_blocks(0)) == 3
    cache.ensure_capacity(0, 13)
    assert len(cache.lane_blocks(0)) == 4
    # Freeing returns every block; the table is reusable by a new lane.
    freed = cache.lane_blocks(0)
    cache.free_lane(0)
    assert cache.allocator.num_free == 6
    cache.alloc_lane(1, prompt_len=16)
    assert set(cache.lane_blocks(1)) & set(freed)  # blocks are recycled
    with pytest.raises(RuntimeError, match="max_seq_len"):
        cache.ensure_capacity(1, 25)


def test_cache_admission_control():
    cache = PagedKVCache(n_layers=1, kv_heads=1, head_dim=4, num_blocks=4,
                         block_size=4, max_lanes=4, max_seq_len=16)
    assert cache.can_admit(16)
    cache.alloc_lane(0, prompt_len=12)         # 3 of 4 blocks
    assert cache.can_admit(4) and not cache.can_admit(5)


# ---------------------------------------------------------------------------
# Paged attention: kernel (interpret) vs dense reference
# ---------------------------------------------------------------------------

def test_paged_kv_update_masks_invalid_lanes():
    nb, bs, kh, d = 4, 4, 2, 8
    k_pool = jnp.zeros((nb, bs, kh, d))
    v_pool = jnp.zeros((nb, bs, kh, d))
    k_new = jnp.ones((2, 1, kh, d))
    v_new = jnp.ones((2, 1, kh, d))
    tables = jnp.array([[1, 2], [3, 0]], jnp.int32)
    positions = jnp.array([[0], [5]], jnp.int32)
    valid = jnp.array([[True], [False]])
    k2, v2 = paged_kv_update(k_pool, v_pool, k_new, v_new, tables,
                             positions, valid)
    assert float(k2[1, 0].sum()) == kh * d      # lane 0 wrote block 1 slot 0
    # The invalid lane wrote nowhere — pool otherwise untouched.
    assert float(k2.sum()) == kh * d
    assert float(v2.sum()) == kh * d


@pytest.mark.parametrize("q_per_kv", [1, 4])
def test_paged_decode_kernel_matches_reference(q_per_kv):
    rng = np.random.default_rng(0)
    b, kh, d, bs, mb = 3, 2, 64, 8, 4
    h = kh * q_per_kv
    nb = 16
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((nb, bs, kh, d)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((nb, bs, kh, d)), jnp.float32)
    tables = jnp.asarray(rng.permutation(nb)[:b * mb].reshape(b, mb),
                         jnp.int32)
    ctx_lens = jnp.asarray([5, 17, 32], jnp.int32)   # partial/multi/full
    out_k = paged_decode_attention(q, k_pool, v_pool, tables, ctx_lens,
                                   use_kernel=True, interpret=True)
    out_ref = paged_attention_reference(
        q[:, None], k_pool, v_pool, tables, ctx_lens,
        (ctx_lens - 1)[:, None])[:, 0]
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# Cached decode == full forward (the correctness core of the engine)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_cached_logits_match_full_forward(family):
    model = gpt if family == "gpt" else llama
    config = model.CONFIGS["nano" if family == "gpt" else "llama-tiny"]
    params = model.init_params(config, jax.random.key(1))
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, config.vocab_size, size=21).tolist()
    prefill = 6

    full = model.forward(params, jnp.asarray([tokens], jnp.int32), config)
    if isinstance(full, tuple):                 # gpt returns (logits, aux)
        full = full[0]
    full = np.asarray(full[0], np.float32)      # [n, vocab]

    n = len(tokens)
    block_size = 8
    cache = PagedKVCache.for_model(
        model, config, num_blocks=-(-n // block_size) + 1,
        block_size=block_size, max_lanes=1, max_seq_len=config.max_seq_len)
    cache.alloc_lane(0, n)

    got = {}

    def run(chunk, start):
        t = len(chunk)
        x, k, v = model.forward_cached(
            params, jnp.asarray([chunk], jnp.int32),
            jnp.asarray([np.arange(start, start + t)], jnp.int32),
            jnp.ones((1, t), bool), cache.k, cache.v,
            cache.device_tables(), jnp.asarray([start + t], jnp.int32),
            config)
        cache.update_pools(k, v)
        got[start + t - 1] = np.asarray(
            model.lm_head(params, x[:, -1], config)[0], np.float32)

    run(tokens[:prefill], 0)                    # chunked prefill
    for i in range(prefill, n):                 # then position > 0 decode
        run(tokens[i:i + 1], i)

    for pos, logits in got.items():
        np.testing.assert_allclose(logits, full[pos], atol=2e-4, rtol=2e-4,
                                   err_msg=f"{family} position {pos}")


# ---------------------------------------------------------------------------
# Continuous batching: lane admission mid-flight
# ---------------------------------------------------------------------------

def test_engine_admits_waiting_request_mid_flight():
    eng = InferenceEngine("gpt", "nano", max_lanes=2, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=0)
    h1 = eng.submit([3, 1, 4], max_new_tokens=3)
    h2 = eng.submit([2, 7, 1], max_new_tokens=12)
    h3 = eng.submit([5, 9, 2], max_new_tokens=3)
    assert eng.num_waiting == 3

    saw_mid_flight_admission = False
    while eng.step():
        # The third request must enter lane 0/1 while the long request
        # is still mid-generation — no batch barrier.
        if eng.num_waiting == 0 and eng.num_active == 2 and \
                h1.finish_reason == "length" and \
                h2.finish_reason is None:
            saw_mid_flight_admission = True
    assert saw_mid_flight_admission
    assert len(h1.tokens()) == 3
    assert len(h2.tokens()) == 12
    assert len(h3.tokens()) == 3
    # Everything was freed on finish.
    assert eng.num_active == 0
    assert eng.cache.allocator.num_free == eng.cache.allocator.num_blocks

    # Batched-greedy output equals one-at-a-time generation.
    solo = InferenceEngine("gpt", "nano", params=eng.params, max_lanes=1,
                           block_size=8, prefill_chunk=4, auto_start=False)
    eng2 = InferenceEngine("gpt", "nano", params=eng.params, max_lanes=2,
                           block_size=8, prefill_chunk=4, auto_start=False)
    hs = [eng2.submit(p, max_new_tokens=5)
          for p in ([3, 1, 4], [2, 7, 1], [5, 9, 2])]
    while eng2.step():
        pass
    batched = [h.tokens() for h in hs]
    for prompt, got in zip(([3, 1, 4], [2, 7, 1], [5, 9, 2]), batched):
        assert got == solo.generate(prompt, max_new_tokens=5)


def test_engine_temperature_sampling_and_eos():
    eng = InferenceEngine("gpt", "nano", max_lanes=1, block_size=8,
                          prefill_chunk=4, auto_start=False, seed=7)
    toks = eng.generate([1, 2, 3], max_new_tokens=50, temperature=1.0)
    assert 0 < len(toks) <= 50
    assert all(0 <= t < eng.config.vocab_size for t in toks)
    # eos_id cuts generation short the moment it is sampled.
    greedy = eng.generate([1, 2, 3], max_new_tokens=8)
    if len(greedy) > 1:
        h = eng.submit([1, 2, 3], max_new_tokens=8, eos_id=greedy[0])
        while eng.step():
            pass
        assert h.tokens() == greedy[:1]
        assert h.finish_reason == "eos"


# ---------------------------------------------------------------------------
# Serve integration: streaming generation end-to-end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    import ray_tpu
    from ray_tpu import serve
    info = ray_tpu.init(num_cpus=8, object_store_memory=64 << 20)
    serve.start()
    yield info
    serve.shutdown()
    ray_tpu.shutdown()


def test_llm_deployment_streams_tokens(cluster):
    from ray_tpu import serve
    handle = serve.run(serve.LLMDeployment.bind(
        model="gpt", config="nano", max_lanes=4, block_size=8,
        prefill_chunk=4))
    prompt = [3, 14, 15, 9]
    streamed = list(handle.options("generate").stream(
        prompt, max_new_tokens=6))
    assert len(streamed) == 6
    assert all(isinstance(t, int) for t in streamed)
    # Non-streaming call agrees with the streamed tokens (greedy).
    assert handle.remote(prompt, 6).result(timeout=60) == streamed
    stats = handle.stats.remote().result(timeout=60)
    assert stats["active"] == 0 and stats["max_lanes"] == 4
    serve.delete("llm")
