"""Native shared-memory object store tests.

Coverage model: the reference's plasma tests
(/root/reference/src/ray/object_manager/plasma/test/) — create/seal/get,
eviction, delete-with-refs, cross-process attach.
"""

import multiprocessing
import os

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_store import ObjectStore
from ray_tpu.exceptions import ObjectStoreFullError, RayTpuTimeoutError


def oid(i=0):
    return ObjectID.for_return(TaskID.of(), i)


def test_put_get_roundtrip(tmp_store):
    o = oid()
    tmp_store.put_bytes(o, b"hello world", b"meta")
    buf = tmp_store.get(o)
    assert bytes(buf.data) == b"hello world"
    assert buf.metadata == b"meta"
    buf.release()


def test_zero_copy_numpy(tmp_store):
    o = oid()
    arr = np.arange(1024, dtype=np.float32)
    view = tmp_store.create_object(o, arr.nbytes)
    np.frombuffer(view, dtype=np.float32)[:] = arr
    tmp_store.seal(o)
    buf = tmp_store.get(o)
    out = np.frombuffer(buf.data, dtype=np.float32)
    np.testing.assert_array_equal(out, arr)
    buf.release()


def test_get_missing_nonblocking(tmp_store):
    assert tmp_store.get(oid()) is None


def test_get_timeout(tmp_store):
    with pytest.raises(RayTpuTimeoutError):
        tmp_store.get(oid(), timeout_ms=50)


def test_unsealed_not_gettable(tmp_store):
    o = oid()
    tmp_store.create_object(o, 10)
    assert tmp_store.get(o) is None
    assert not tmp_store.contains(o)
    tmp_store.seal(o)
    assert tmp_store.contains(o)


def test_double_create_fails(tmp_store):
    o = oid()
    tmp_store.put_bytes(o, b"x")
    with pytest.raises(RuntimeError):
        tmp_store.create_object(o, 5)


def test_delete_and_deferred_delete(tmp_store):
    o = oid()
    tmp_store.put_bytes(o, b"x" * 100)
    buf = tmp_store.get(o)
    tmp_store.delete(o)  # deferred: buf still holds a ref
    assert bytes(buf.data) == b"x" * 100
    buf.release()
    assert not tmp_store.contains(o)


def test_lru_eviction(tmp_path):
    store = ObjectStore.create(str(tmp_path / "s.shm"), 1 << 20)
    try:
        ids = [oid(i) for i in range(8)]
        for i, o in enumerate(ids):
            store.put_bytes(o, bytes([i]) * (200 << 10))
        # 1 MiB heap holds ~4 of these 200 KiB objects: oldest were evicted.
        assert not store.contains(ids[0])
        assert store.contains(ids[-1])
        assert store.stats()["num_evictions"] > 0
    finally:
        store.close()


def test_pinned_objects_not_evicted(tmp_path):
    store = ObjectStore.create(str(tmp_path / "s.shm"), 1 << 20)
    try:
        pinned = oid(0)
        store.put_bytes(pinned, b"p" * (600 << 10))
        buf = store.get(pinned)  # pin it
        with pytest.raises(ObjectStoreFullError):
            store.put_bytes(oid(1), b"q" * (600 << 10))
        buf.release()
        store.put_bytes(oid(1), b"q" * (600 << 10))  # now evictable
        assert not store.contains(pinned)
    finally:
        store.close()


def test_abort(tmp_store):
    o = oid()
    tmp_store.create_object(o, 1000)
    used_before = tmp_store.stats()["used"]
    tmp_store.abort(o)
    assert tmp_store.stats()["used"] < used_before
    assert tmp_store.get(o) is None


def _child_put(path, id_bytes):
    store = ObjectStore.attach(path)
    store.put_bytes(ObjectID(id_bytes), b"from child", b"m")
    store.close()
    os._exit(0)


def test_cross_process(tmp_path):
    path = str(tmp_path / "s.shm")
    store = ObjectStore.create(path, 4 << 20)
    try:
        o = oid()
        ctx = multiprocessing.get_context("fork")
        p = ctx.Process(target=_child_put, args=(path, o.binary()))
        p.start()
        buf = store.get(o, timeout_ms=5000)  # blocks until child seals
        assert bytes(buf.data) == b"from child"
        buf.release()
        p.join(timeout=10)
    finally:
        store.close()


def _child_crash_holding_refs(path, unsealed_id, pinned_id):
    store = ObjectStore.attach(path)
    store.create_object(ObjectID(unsealed_id), 200 << 10)  # never sealed
    buf = store.get(ObjectID(pinned_id))  # pin a sealed object
    assert buf is not None
    os.kill(os.getpid(), 9)  # die without releasing anything


def test_dead_client_reclamation(tmp_path):
    """A SIGKILLed client's pinned refs and unsealed creations must not leak
    capacity: the reclaim pass (run inline on OOM) frees them."""
    path = str(tmp_path / "s.shm")
    store = ObjectStore.create(path, 1 << 20)
    try:
        pinned = oid(0)
        store.put_bytes(pinned, b"p" * (300 << 10))
        unsealed = oid(1)
        ctx = multiprocessing.get_context("fork")
        p = ctx.Process(target=_child_crash_holding_refs,
                        args=(path, unsealed.binary(), pinned.binary()))
        p.start()
        p.join(timeout=20)
        # Child died holding: a 200 KiB unsealed object + a ref pinning the
        # 300 KiB sealed one.  A 600 KiB put only fits if both are reclaimed.
        big = oid(2)
        store.put_bytes(big, b"q" * (600 << 10))
        assert store.contains(big)
        assert store.get(unsealed) is None
    finally:
        store.close()


def test_many_objects_reuse_space(tmp_path):
    store = ObjectStore.create(str(tmp_path / "s.shm"), 1 << 20)
    try:
        for i in range(500):
            o = oid(i)
            store.put_bytes(o, b"z" * 4096)
            store.delete(o)
        assert store.stats()["num_objects"] == 0
    finally:
        store.close()
