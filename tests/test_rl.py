"""Podracer RL substrate tests (PR 20): trajectory queue semantics,
in-place engine weight publication, versioned rollouts, the
stale-tolerant V-trace learner, and the two chaos gates (rollout-worker
kill -> re-form + re-adopt; learner kill -> resume from COMMITTED).

Learning-curve gates (parity vs sync PPO at k=0; still-learns at k=2)
are @slow — they run real CartPole training loops.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (
    EngineRolloutActor,
    Podracer,
    PodracerConfig,
    StaleTolerantLearner,
    TrajectoryQueue,
    WeightPublisher,
)
from ray_tpu.rllib.sample_batch import SampleBatch


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Trajectory queue: staleness bound + backpressure
# ---------------------------------------------------------------------------


def test_trajectory_queue_staleness_and_backpressure():
    q = TrajectoryQueue(capacity=2, staleness_bound=1)
    assert q.put("a", version=5, learner_version=5)
    assert q.put("b", version=4, learner_version=5)      # staleness 1: ok
    assert not q.put("c", version=3, learner_version=5)  # staleness 2: drop
    assert not q.put("d", version=5, learner_version=5)  # full: backpressure
    assert q.full and len(q) == 2
    st = q.stats()
    assert st["accepted"] == 2
    assert st["stale_dropped"] == 1
    assert st["backpressured"] == 1

    batch, version = q.get(learner_version=5)
    assert (batch, version) == ("a", 5)
    # "b" (version 4) went stale while queued once the learner hits 6:
    # get() must evict it in passing, not hand it over.
    assert q.get(learner_version=6) is None
    assert q.stats()["stale_dropped"] == 2
    assert len(q) == 0


def test_trajectory_queue_get_timeout_and_evict_stale():
    q = TrajectoryQueue(capacity=4, staleness_bound=0)
    t0 = time.monotonic()
    assert q.get(learner_version=1, timeout=0.05) is None
    assert time.monotonic() - t0 >= 0.04
    for v in (1, 2, 3):
        assert q.put(f"b{v}", version=v, learner_version=3 if v == 3 else v)
    # Learner resumed at version 3: only the version-3 entry survives.
    assert q.evict_stale(learner_version=3) == 2
    assert q.get(learner_version=3) == ("b3", 3)
    with pytest.raises(ValueError):
        TrajectoryQueue(capacity=0)
    with pytest.raises(ValueError):
        TrajectoryQueue(staleness_bound=-1)


# ---------------------------------------------------------------------------
# Engine path: in-place weight swap + versioned logp-carrying rollouts
# ---------------------------------------------------------------------------


def test_engine_weight_swap_mid_flight_keeps_lanes():
    """update_params between scheduler steps must not drop the in-flight
    lane: the request finishes its full budget, the engine reports the
    new policy version, and every emitted token carries a log-prob."""
    actor = EngineRolloutActor("gpt", "nano", max_lanes=2,
                               temperature=1.0, seed=0)
    eng = actor.engine
    h = eng.submit(list(range(1, 9)), max_new_tokens=8, temperature=1.0,
                   seed=7)
    for _ in range(3):
        assert eng.step()
    new_version = actor.adopt(7, eng.params)   # swap mid-request
    assert new_version == 7
    while eng.step():
        pass
    assert len(h.tokens()) == 8
    assert len(h.logps) == 8
    assert all(np.isfinite(lp) and lp <= 0.0 for lp in h.logps)
    assert eng.policy_version == 7
    assert eng.stats()["policy_version"] == 7


def test_engine_rollout_actor_versioned_batch():
    """rollout() emits a time-major V-trace-shaped SampleBatch tagged
    with the producing policy version; adoption re-tags the next batch."""
    rewards_seen = []

    def reward_fn(prompt, completion):
        rewards_seen.append((tuple(prompt), tuple(completion)))
        return float(len(completion))

    actor = EngineRolloutActor("gpt", "nano", max_lanes=4, temperature=1.0,
                               seed=0, reward_fn=reward_fn)
    prompts = [[1, 2, 3], [1, 2, 4], [1, 2, 5]]
    batch, version, metrics = actor.rollout(prompts, max_new_tokens=6,
                                            seed=11)
    assert version == 0
    T, B = batch[SampleBatch.ACTIONS].shape
    assert B == 3 and 1 <= T <= 6
    for key in (SampleBatch.ACTION_LOGP, SampleBatch.REWARDS,
                SampleBatch.TERMINATEDS, "valid", "policy_version"):
        assert batch[key].shape == (T, B)
    assert (batch["policy_version"] == 0).all()
    # Each lane terminates exactly once, where its terminal reward sits.
    assert batch[SampleBatch.TERMINATEDS].sum(axis=0).tolist() == [1, 1, 1]
    n_valid = batch["valid"].sum(axis=0)
    for b in range(B):
        t_last = int(n_valid[b]) - 1
        assert batch[SampleBatch.TERMINATEDS][t_last, b]
        assert batch[SampleBatch.REWARDS][t_last, b] == float(n_valid[b])
    assert len(rewards_seen) == 3
    assert metrics["tokens"] == int(batch["valid"].sum())
    assert metrics["tokens_per_s"] > 0

    actor.adopt(4, actor.engine.params)
    batch2, version2, _ = actor.rollout(prompts, max_new_tokens=4, seed=12)
    assert version2 == 4 and (batch2["policy_version"] == 4).all()


# ---------------------------------------------------------------------------
# Stale-tolerant learner: staleness accounting + COMMITTED durability
# ---------------------------------------------------------------------------


def _fake_fragment(rng, T=8, B=4, obs_dim=4, num_actions=2):
    return SampleBatch({
        SampleBatch.OBS: rng.normal(size=(T, B, obs_dim)).astype(np.float32),
        SampleBatch.ACTIONS: rng.integers(0, num_actions,
                                          size=(T, B)).astype(np.int32),
        SampleBatch.ACTION_LOGP: np.full((T, B), -0.7, np.float32),
        SampleBatch.REWARDS: rng.normal(size=(T, B)).astype(np.float32),
        SampleBatch.TERMINATEDS: np.zeros((T, B), np.bool_),
        SampleBatch.TRUNCATEDS: np.zeros((T, B), np.bool_),
        "bootstrap_obs": rng.normal(size=(B, obs_dim)).astype(np.float32),
        "policy_version": np.ones((T, B), np.int32),
        "valid": np.ones((T, B), np.bool_),
    })


def test_learner_staleness_versioning_and_checkpoint_resume(tmp_path):
    rng = np.random.default_rng(0)
    ln = StaleTolerantLearner(4, 2, hidden=(8,), seed=0,
                              ckpt_dir=str(tmp_path), ckpt_interval=2)
    assert ln.version == 1
    m1 = ln.update(_fake_fragment(rng), behavior_version=1)
    assert m1["staleness"] == 0.0 and np.isfinite(m1["total_loss"])
    version, weights = ln.publish_boundary()
    assert version == 2 and weights is not None
    m2 = ln.update(_fake_fragment(rng), behavior_version=1)
    assert m2["staleness"] == 1.0
    # ckpt_interval=2 -> a COMMITTED checkpoint exists at update 2.
    ln2 = StaleTolerantLearner(4, 2, hidden=(8,), seed=123,
                               ckpt_dir=str(tmp_path))
    restored = ln2.restore_latest()
    assert restored == 2
    assert ln2.version == 2 and ln2.num_updates == 2
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(ln.get_weights()),
                    jax.tree_util.tree_leaves(ln2.get_weights())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Fresh dir: nothing to restore.
    ln3 = StaleTolerantLearner(4, 2, hidden=(8,), seed=0,
                               ckpt_dir=str(tmp_path / "empty"))
    assert ln3.restore_latest() is None


# ---------------------------------------------------------------------------
# Chaos gates: rollout-worker kill + learner kill, one live cluster
# ---------------------------------------------------------------------------


def test_podracer_chaos_worker_kill_and_learner_resume(cluster, tmp_path):
    cfg = (PodracerConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                     rollout_fragment_length=8)
           .training(min_updates_per_step=2, staleness_bound=2,
                     queue_capacity=4, ckpt_dir=str(tmp_path),
                     ckpt_interval=1)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        r = algo.train()
        assert r["learner_updates_total"] >= 2
        assert r["policy_version"] >= 2

        # Gate 1: kill a rollout worker mid-gang.  The loop must detect
        # the death at delivery, re-form the gang, and the replacement
        # must re-adopt the CURRENT published weights (no new put).
        ray_tpu.kill(algo.workers.remote_workers[0])
        for _ in range(3):
            r = algo.train()
        assert algo.workers.num_remote_workers == 2
        versions = ray_tpu.get(
            [w.get_version.remote() for w in algo.workers.remote_workers])
        assert all(v >= 1 for v in versions)
        # The gang converges onto the newest published version.
        r = algo.train()
        versions = ray_tpu.get(
            [w.get_version.remote() for w in algo.workers.remote_workers])
        assert max(versions) == algo.publisher.version

        # Gate 2: kill the learner.  Resume must come from the newest
        # COMMITTED checkpoint and must not poison the queue — entries
        # beyond the restored staleness horizon are evicted, training
        # continues.
        updates_before = algo.learner.num_updates
        committed = algo.learner._ckpt.latest_step()
        assert committed is not None and committed <= updates_before
        algo.learner = None   # the "kill": in-memory state is gone
        restored = algo.recover_learner()
        assert restored == committed
        assert algo.learner.num_updates == committed
        for _, v in list(algo.queue._dq):
            assert algo.learner.version - v <= algo.queue.staleness_bound
        r = algo.train()
        assert algo.learner.num_updates > committed
        assert np.isfinite(r["learner/total_loss"])
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# Learning gates (slow): parity vs sync PPO at k=0; still learns at k=2
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_podracer_k0_parity_with_sync_ppo(cluster):
    """At staleness_bound=0 every trained batch is exactly on-policy, so
    the async loop is a sync actor-learner with extra plumbing — it must
    reach the same CartPole milestone as rllib's synchronous PPO within
    a bounded sample-budget factor.  The 6x tolerance is measured
    headroom, not hand-waving: PPO does 6 SGD epochs per batch where
    V-trace trains each fragment once, and at k=0 roughly half the
    produced fragments are dropped at publish boundaries (the async
    loop's on-policy tax) — observed ratio ~4.3x."""
    from ray_tpu.rllib import PPOConfig

    TARGET = 100.0

    def steps_to_target_ppo(budget_steps):
        cfg = (PPOConfig().environment("CartPole-v1")
               .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                         rollout_fragment_length=32)
               .training(train_batch_size=512, sgd_minibatch_size=128,
                         num_sgd_iter=6, lr=5e-4, entropy_coeff=0.005)
               .debugging(seed=1))
        algo = cfg.build()
        try:
            while algo.total_env_steps < budget_steps:
                r = algo.train()
                if r["episode_reward_mean"] >= TARGET:
                    return algo.total_env_steps
            return None
        finally:
            algo.stop()

    def steps_to_target_podracer(budget_steps):
        cfg = (PodracerConfig().environment("CartPole-v1")
               .rollouts(num_rollout_workers=1, num_envs_per_worker=16,
                         rollout_fragment_length=32)
               .training(staleness_bound=0, publish_interval=1,
                         min_updates_per_step=2, lr=1e-3,
                         entropy_coeff=0.005)
               .debugging(seed=1))
        algo = cfg.build()
        steps = 0
        try:
            while steps < budget_steps:
                r = algo.train()
                steps += r["fragments_this_iter"] * 16 * 32
                assert r.get("learner/staleness", 0.0) == 0.0
                if r["episode_reward_mean"] >= TARGET:
                    return steps
            return None
        finally:
            algo.stop()

    ppo_steps = steps_to_target_ppo(120_000)
    assert ppo_steps is not None, "sync PPO baseline failed its own gate"
    pod_steps = steps_to_target_podracer(6 * ppo_steps)
    assert pod_steps is not None, \
        f"podracer@k=0 did not reach {TARGET} within 6x PPO's " \
        f"{ppo_steps} env steps"


@pytest.mark.slow
def test_podracer_still_learns_at_k2(cluster):
    """With staleness_bound=2 and a publish per update, most batches are
    trained off-policy — V-trace must still move reward well off the
    random floor, and the loop must actually have trained stale data."""
    cfg = (PodracerConfig().environment("CartPole-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                     rollout_fragment_length=32)
           .training(staleness_bound=2, publish_interval=1,
                     min_updates_per_step=2, lr=5e-4, entropy_coeff=0.01)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best, max_staleness = 0.0, 0.0
        for _ in range(60):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            max_staleness = max(max_staleness,
                                r.get("learner/staleness", 0.0))
            if best > 60 and max_staleness > 0:
                break
        assert best > 60, f"podracer@k=2 made no progress: best={best}"
        assert max_staleness > 0, "async loop never trained a stale batch"
        assert max_staleness <= 2, \
            f"staleness bound violated: {max_staleness}"
    finally:
        algo.stop()
