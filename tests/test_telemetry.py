"""Pull-based telemetry endpoint suite (PR 11).

`/metrics` serves parseable Prometheus exposition text, `/events`
filtered JSON, `/healthz` identity — on both hostd and the driver, with
ports discovered through the `proc/telemetry_listen` ring event.  With
``RAY_TPU_EVENTS=0`` nothing binds.
"""

import json
import re
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import state
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.util import events, telemetry


@pytest.fixture(autouse=True)
def _fresh_recorder():
    events.reset()
    yield
    events.reset()
    GLOBAL_CONFIG.invalidate_cache()


@pytest.fixture
def cluster():
    info = ray_tpu.init(num_cpus=2, object_store_memory=64 << 20)
    try:
        yield info
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()


def _endpoints(deadline_s: float = 10.0):
    """component -> (host, port) from the announce events."""
    deadline = time.time() + deadline_s
    found = {}
    while time.time() < deadline:
        for e in state.events(kind="telemetry_listen"):
            p = e.get("payload") or {}
            if "port" in p:
                found[p.get("component")] = (p.get("host"), p["port"])
        if {"hostd", "driver"} <= set(found):
            return found
        time.sleep(0.2)
    return found


def _get(host, port, path, timeout=5):
    with urllib.request.urlopen(
            f"http://{host}:{port}{path}", timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


# Prometheus exposition text: comment/blank lines, or `name{labels} value`.
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})?\s+'
    r'([+-]?(\d+\.?\d*([eE][+-]?\d+)?|Inf|NaN))$')


def _parse_prometheus(text: str):
    """Minimal exposition-format check; returns (families, samples)."""
    families, samples = set(), 0
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            families.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        samples += 1
    return families, samples


def test_metrics_endpoints_serve_prometheus_text(cluster):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    eps = _endpoints()
    assert "hostd" in eps, f"hostd never announced an endpoint: {eps}"
    assert "driver" in eps

    status, ctype, body = _get(*eps["hostd"], "/metrics")
    assert status == 200 and ctype.startswith("text/plain")
    families, samples = _parse_prometheus(body.decode())
    assert samples > 0 and families
    assert 'component="hostd"' in body.decode()

    status, _, body = _get(*eps["driver"], "/metrics")
    assert status == 200
    _parse_prometheus(body.decode())
    assert 'component="driver"' in body.decode()


def test_events_endpoint_filters_json(cluster):
    events.record("serve", "admit", deployment="d1")
    events.record("sched", "grant", n=1)
    eps = _endpoints()
    host, port = eps["driver"]

    status, ctype, body = _get(host, port, "/events?plane=serve")
    assert status == 200 and ctype.startswith("application/json")
    doc = json.loads(body)
    assert doc["count"] == len(doc["events"]) > 0
    assert all(e["plane"] == "serve" for e in doc["events"])

    _, _, body = _get(host, port, "/events?plane=serve&kind=nope")
    assert json.loads(body)["count"] == 0

    _, _, body = _get(host, port, f"/events?since={time.time() + 60}")
    assert json.loads(body)["count"] == 0

    _, _, body = _get(host, port, "/events?limit=1")
    assert json.loads(body)["count"] == 1

    # hostd's endpoint serves the node-level merge (worker rings too).
    status, _, body = _get(*eps["hostd"], "/events")
    assert status == 200
    assert json.loads(body)["count"] >= 0


def test_healthz(cluster):
    eps = _endpoints()
    status, _, body = _get(*eps["hostd"], "/healthz")
    assert status == 200
    h = json.loads(body)
    assert h["ok"] is True and h["component"] == "hostd"
    assert "node_id" in h and "workers" in h

    status, _, body = _get(*eps["driver"], "/healthz")
    assert json.loads(body)["component"] == "driver"


def test_unknown_path_404(cluster):
    eps = _endpoints()
    host, port = eps["driver"]
    try:
        _get(host, port, "/nope")
        raised = False
    except urllib.error.HTTPError as e:
        raised = e.code == 404
    assert raised


def test_disabled_when_events_off(monkeypatch):
    monkeypatch.setenv("RAY_TPU_EVENTS", "0")
    GLOBAL_CONFIG.invalidate_cache()
    events.reset()
    srv = telemetry.start_server(metrics_fn=lambda: "",
                                 events_fn=lambda *a: [],
                                 component="test")
    assert srv is None


def test_disabled_when_port_negative(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TELEMETRY_PORT", "-1")
    GLOBAL_CONFIG.invalidate_cache()
    events.reset()
    srv = telemetry.start_server(metrics_fn=lambda: "",
                                 events_fn=lambda *a: [],
                                 component="test")
    assert srv is None
