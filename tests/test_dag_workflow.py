"""DAG + Workflow tests.

Reference coverage model: python/ray/dag/tests/ (bind/execute chains,
shared nodes, actor method nodes) and python/ray/workflow/tests/
(durable execution, resume skips completed steps, failure recovery).
"""

import os

import pytest

import ray_tpu
from ray_tpu.dag import InputNode


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_dag_function_chain(cluster):
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x):
        return x * 2

    @ray_tpu.remote
    def combine(x, y):
        return x + y

    with InputNode() as inp:
        dag = combine.bind(a.bind(inp), b.bind(inp))
    assert ray_tpu.get(dag.execute(10)) == 11 + 20
    assert ray_tpu.get(dag.execute(0)) == 1


def test_dag_shared_node_executes_once(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    counter = Counter.remote()

    @ray_tpu.remote
    def expensive(c):
        import ray_tpu as rt
        return rt.get(c.bump.remote())

    @ray_tpu.remote
    def add(x, y):
        return x + y

    shared = expensive.bind(counter)
    dag = add.bind(shared, shared)
    assert ray_tpu.get(dag.execute()) == 2  # 1 + 1: shared ran ONCE
    assert ray_tpu.get(counter.bump.remote()) == 2


def test_dag_actor_nodes(cluster):
    @ray_tpu.remote
    class Adder:
        def __init__(self, base):
            self.base = base

        def add(self, x):
            return self.base + x

    with InputNode() as inp:
        actor = Adder.bind(100)
        dag = actor.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 105


def test_workflow_durable_run_and_resume(cluster, tmp_path):
    from ray_tpu import workflow

    workflow.init(str(tmp_path))
    marker = tmp_path / "exec_count"

    @ray_tpu.remote
    def step_a():
        with open(marker, "a") as f:
            f.write("a")
        return 10

    @ray_tpu.remote
    def flaky(x):
        if not os.path.exists(str(marker) + ".allow"):
            raise RuntimeError("transient failure")
        return x * 3

    dag = flaky.bind(step_a.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf1")
    assert workflow.get_status("wf1") == "FAILED"
    assert marker.read_text() == "a"  # step_a ran exactly once

    # Heal the environment, resume: step_a must NOT re-run.
    open(str(marker) + ".allow", "w").close()
    assert workflow.resume("wf1") == 30
    assert marker.read_text() == "a"
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 30
    wfs = workflow.list_all()
    assert any(w["workflow_id"] == "wf1"
               and w["status"] == "SUCCESSFUL" for w in wfs)


def test_workflow_run_async(cluster, tmp_path):
    from ray_tpu import workflow

    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def one():
        return 1

    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = workflow.run_async(inc.bind(one.bind()), workflow_id="wfa")
    assert ray_tpu.get(ref, timeout=60) == 2
    assert workflow.get_output("wfa") == 2


def test_workflow_event_providers(cluster, tmp_path):
    """Event steps (reference: workflow.wait_for_event +
    http_event_provider.py): a workflow blocks on an external HTTP event,
    consumes its payload, and a RESUMED workflow replays the checkpointed
    payload instead of waiting again."""
    import json
    import threading
    import time
    import urllib.request

    from ray_tpu import workflow
    from ray_tpu.workflow import events

    workflow.init(str(tmp_path / "wf"))
    provider = events.HTTPEventProvider(port=0)
    try:
        @ray_tpu.remote
        def consume(event):
            return {"got": event["ok"], "stamp": time.time()}

        dag = consume.bind(
            events.event_step.bind(provider.listener("approval")))

        def post_later():
            time.sleep(1.0)
            req = urllib.request.Request(
                provider.address + "/event/approval",
                data=json.dumps({"ok": 42}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10).read()

        threading.Thread(target=post_later, daemon=True).start()
        t0 = time.time()
        out = workflow.run(dag, workflow_id="evt1")
        assert out["got"] == 42
        assert time.time() - t0 >= 0.9  # actually waited for the POST

        # Delivered-state introspection via GET.
        got = json.loads(urllib.request.urlopen(
            provider.address + "/event/approval", timeout=10).read())
        assert got["delivered"]

        # Resume replays the checkpointed event payload without waiting.
        t1 = time.time()
        out2 = workflow.resume("evt1")
        assert out2["got"] == 42 and time.time() - t1 < 0.9
    finally:
        provider.stop()
