"""Native task-transport (taskrpc.cc) unit tests, exercised directly
through the ctypes binding without a cluster.

Reference parity: src/ray/core_worker/transport/direct_task_transport.h:75
(pipelined PushTask) — here the framed-TCP client/server pair plus the
batched completion pump.
"""

import asyncio
import threading

import pytest

from ray_tpu._private import task_transport as tt


@pytest.fixture
def loop_thread():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    yield loop
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)


def _roundtrip(submitter, receiver, loop, payload, timeout=15):
    async def go():
        return await asyncio.wait_for(
            submitter.call(f"127.0.0.1:{receiver.port}", payload), timeout)
    return asyncio.run_coroutine_threadsafe(go(), loop).result(timeout + 5)


def test_pipelined_roundtrip_order(loop_thread):
    seen = []
    r = tt.NativeReceiver(
        lambda payload, reply: (seen.append(payload), reply(payload + b"!")))
    s = tt.NativeSubmitter(loop_thread)
    try:
        async def go():
            futs = [s.call(f"127.0.0.1:{r.port}", b"m%d" % i)
                    for i in range(200)]
            return await asyncio.wait_for(asyncio.gather(*futs), 30)
        outs = asyncio.run_coroutine_threadsafe(go(), loop_thread).result(40)
        assert outs == [b"m%d!" % i for i in range(200)]
        # Per-connection FIFO: the receiver saw submission order.
        assert seen == [b"m%d" % i for i in range(200)]
    finally:
        s.close()
        r.close()


def test_oversized_record_grows_buffer(loop_thread):
    """A request or reply bigger than the pop/poll buffer must not wedge
    the endpoint (ADVICE r3: pack_records used to leave it queued forever);
    the TPT_EBUF signal makes Python grow its buffer and retry."""

    class SmallReceiver(tt.NativeReceiver):
        POP_BUF = 1024

    class SmallSubmitter(tt.NativeSubmitter):
        POLL_BUF = 1024

    big_reply = b"y" * (2 << 20)
    r = SmallReceiver(lambda payload, reply: reply(big_reply))
    s = SmallSubmitter(loop_thread)
    try:
        # Oversized request (4KB > 1KB pop buf) AND oversized reply (2MB >
        # 1KB poll buf) both cross the wire; later small calls still work
        # (nothing stuck at the queue head).
        out = _roundtrip(s, r, loop_thread, b"x" * 4096)
        assert out == big_reply
        out2 = _roundtrip(s, r, loop_thread, b"tiny")
        assert out2 == big_reply
    finally:
        s.close()
        r.close()


def test_connection_failure_fails_inflight(loop_thread):
    ev = threading.Event()
    r = tt.NativeReceiver(lambda payload, reply: ev.wait(10))  # never replies
    s = tt.NativeSubmitter(loop_thread)
    try:
        async def go():
            fut = s.call(f"127.0.0.1:{r.port}", b"stall")
            await asyncio.sleep(0.2)
            r.close()  # kill server with the request in flight
            with pytest.raises(tt.ConnClosedError):
                await asyncio.wait_for(fut, 10)
            return True
        assert asyncio.run_coroutine_threadsafe(go(), loop_thread).result(20)
    finally:
        ev.set()
        s.close()


# ---------------------------------------------------------------------------
# Native TaskSpec codec (tpt_send_specs): C++ splices template + packed
# descriptor into TaskSpecP/PushTaskRequest wire bytes; upb must parse
# them back to exactly the fields Python would have encoded.
# ---------------------------------------------------------------------------


def _spec_roundtrip(loop, descs, caller=b"caller-01", templates=()):
    """Send packed descriptors through a loopback pair; return the decoded
    PushTaskRequest protos in receive order."""
    from ray_tpu.protocol import pb

    got = []
    done = threading.Event()
    want = len(descs)

    def handler(payload, reply):
        got.append(pb.PushTaskRequest.FromString(payload))
        reply(b"ok")
        if len(got) == want:
            done.set()

    r = tt.NativeReceiver(handler)
    s = tt.NativeSubmitter(loop)
    try:
        s.set_caller(caller)
        acks = []

        def run():
            items = [(d, tpl, lambda st, data: acks.append(st))
                     for d, tpl in zip(descs, templates)]
            s.call_spec_batch(f"127.0.0.1:{r.port}", items)

        loop.call_soon_threadsafe(run)
        assert done.wait(15)
        return got
    finally:
        s.close()
        r.close()


def test_native_spec_codec_matches_python_encoding(loop_thread):
    """C-encoded wire bytes must decode to the same TaskSpec the pure-
    Python encoder (convert.taskspec_to_proto) would produce."""
    from ray_tpu._private import spec_codec
    from ray_tpu._private.ids import JobID, TaskID
    from ray_tpu._private.protocol import RefArg, Resources, TaskSpec, ValueArg
    from ray_tpu.protocol.convert import taskspec_from_proto

    tid = TaskID.of()
    res = Resources(cpu=2.0, tpu=1.0, custom={"special": 0.5})
    tpl = spec_codec.build_template(
        job_id=b"\x01\x02\x03\x04", name="myfn", fn_key="fnkey-1",
        num_returns=2, resources=res, max_retries=4, retry_exceptions=True,
        owner_address="10.0.0.1:999", runtime_env={"env_vars": {"A": "1"}})
    args = [ValueArg(b"hello-data", b"meta1"),
            RefArg(b"r" * 28, "10.0.0.2:888"),
            ValueArg(b"x" * 300000, b"")]       # >64KB: multi-byte varint
    kwargs = {"kw1": ValueArg(b"kwdata", b""),
              "kw2": RefArg(b"s" * 28, "10.0.0.3:777")}
    trace = b"\x80trace-ctx"
    desc = spec_codec.pack_desc(7, 5, 3, tid.binary(), trace, args, kwargs)

    reqs = _spec_roundtrip(loop_thread, [desc], templates=[(7, tpl)])
    m = reqs[0]
    assert m.caller_id == b"caller-01"
    assert m.wire_seq == 3
    assert m.spec.trace_ctx == trace

    spec = taskspec_from_proto(m.spec)
    assert spec.task_id == tid
    assert spec.job_id.binary() == b"\x01\x02\x03\x04"
    assert spec.name == "myfn" and spec.fn_key == "fnkey-1"
    assert spec.num_returns == 2
    assert spec.max_retries == 4 and spec.retry_exceptions is True
    assert spec.owner_address == "10.0.0.1:999"
    assert spec.resources.cpu == 2.0 and spec.resources.tpu == 1.0
    assert spec.resources.custom == {"special": 0.5}
    assert spec.runtime_env == {"env_vars": {"A": "1"}}
    assert spec.seq_no == 5
    a0, a1, a2 = spec.args
    assert isinstance(a0, ValueArg) and a0.data == b"hello-data" \
        and a0.metadata == b"meta1"
    assert isinstance(a1, RefArg) and a1.id_binary == b"r" * 28 \
        and a1.owner_address == "10.0.0.2:888"
    assert a2.data == b"x" * 300000
    assert spec.kwargs["kw1"].data == b"kwdata"
    assert spec.kwargs["kw2"].id_binary == b"s" * 28
    # Codec tags on inline values (a C++ peer needs them to interpret
    # the bytes): Python-built args are pickle5.
    assert m.spec.args[0].value.codec == "pickle5"


def test_native_spec_codec_batch_and_defaults(loop_thread):
    """A burst shares one library call; zero seq/wire_seq/trace encode to
    proto defaults; an unregistered template is rejected without
    touching earlier state."""
    from ray_tpu._private import spec_codec
    from ray_tpu._private.ids import TaskID
    from ray_tpu._private.protocol import Resources

    tpl = spec_codec.build_template(
        job_id=b"\x00\x00\x00\x01", name="nop", fn_key="k",
        num_returns=1, resources=Resources(), max_retries=0,
        retry_exceptions=False, owner_address="127.0.0.1:1")
    tids = [TaskID.of() for _ in range(50)]
    descs = [spec_codec.pack_desc(1, 0, 0, t.binary(), None, [], {})
             for t in tids]
    reqs = _spec_roundtrip(loop_thread, descs,
                           templates=[(1, tpl)] * len(descs))
    assert [m.spec.task_id for m in reqs] == [t.binary() for t in tids]
    for m in reqs:
        assert m.wire_seq == 0 and m.spec.seq_no == 0
        assert m.spec.trace_ctx == b""
        assert m.spec.name == "nop"
        assert len(m.spec.args) == 0
