"""Native task-transport (taskrpc.cc) unit tests, exercised directly
through the ctypes binding without a cluster.

Reference parity: src/ray/core_worker/transport/direct_task_transport.h:75
(pipelined PushTask) — here the framed-TCP client/server pair plus the
batched completion pump.
"""

import asyncio
import threading

import pytest

from ray_tpu._private import task_transport as tt


@pytest.fixture
def loop_thread():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    yield loop
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)


def _roundtrip(submitter, receiver, loop, payload, timeout=15):
    async def go():
        return await asyncio.wait_for(
            submitter.call(f"127.0.0.1:{receiver.port}", payload), timeout)
    return asyncio.run_coroutine_threadsafe(go(), loop).result(timeout + 5)


def test_pipelined_roundtrip_order(loop_thread):
    seen = []
    r = tt.NativeReceiver(
        lambda payload, reply: (seen.append(payload), reply(payload + b"!")))
    s = tt.NativeSubmitter(loop_thread)
    try:
        async def go():
            futs = [s.call(f"127.0.0.1:{r.port}", b"m%d" % i)
                    for i in range(200)]
            return await asyncio.wait_for(asyncio.gather(*futs), 30)
        outs = asyncio.run_coroutine_threadsafe(go(), loop_thread).result(40)
        assert outs == [b"m%d!" % i for i in range(200)]
        # Per-connection FIFO: the receiver saw submission order.
        assert seen == [b"m%d" % i for i in range(200)]
    finally:
        s.close()
        r.close()


def test_oversized_record_grows_buffer(loop_thread):
    """A request or reply bigger than the pop/poll buffer must not wedge
    the endpoint (ADVICE r3: pack_records used to leave it queued forever);
    the TPT_EBUF signal makes Python grow its buffer and retry."""

    class SmallReceiver(tt.NativeReceiver):
        POP_BUF = 1024

    class SmallSubmitter(tt.NativeSubmitter):
        POLL_BUF = 1024

    big_reply = b"y" * (2 << 20)
    r = SmallReceiver(lambda payload, reply: reply(big_reply))
    s = SmallSubmitter(loop_thread)
    try:
        # Oversized request (4KB > 1KB pop buf) AND oversized reply (2MB >
        # 1KB poll buf) both cross the wire; later small calls still work
        # (nothing stuck at the queue head).
        out = _roundtrip(s, r, loop_thread, b"x" * 4096)
        assert out == big_reply
        out2 = _roundtrip(s, r, loop_thread, b"tiny")
        assert out2 == big_reply
    finally:
        s.close()
        r.close()


def test_connection_failure_fails_inflight(loop_thread):
    ev = threading.Event()
    r = tt.NativeReceiver(lambda payload, reply: ev.wait(10))  # never replies
    s = tt.NativeSubmitter(loop_thread)
    try:
        async def go():
            fut = s.call(f"127.0.0.1:{r.port}", b"stall")
            await asyncio.sleep(0.2)
            r.close()  # kill server with the request in flight
            with pytest.raises(tt.ConnClosedError):
                await asyncio.wait_for(fut, 10)
            return True
        assert asyncio.run_coroutine_threadsafe(go(), loop_thread).result(20)
    finally:
        ev.set()
        s.close()
