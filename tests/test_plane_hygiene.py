"""Static plane hygiene (PR 11 satellite): every literal call site of
``events.record(...)`` / ``spans.begin(...)`` / ``spans.span(...)`` in
the package uses a plane string from ``events.PLANES`` and a sane kind,
and every file that opens spans imperatively also closes them.  Greps
source so a typo'd plane ("sched " / "schedule") fails CI instead of
silently fragmenting the `cli top` per-plane rates.
"""

import pathlib
import re

from ray_tpu.util import events

PKG = pathlib.Path(events.__file__).resolve().parents[1]

# events.record("plane", "kind", ... / spans.begin("plane", "kind", ...
# Payloads stay on later lines; plane+kind may wrap one line break.
_CALL = re.compile(
    r"(?:events\.record|spans\.begin|spans\.span)\(\s*\n?\s*"
    r"(['\"])([^'\"]*)\1\s*,\s*\n?\s*(['\"])([^'\"]*)\3",
    re.MULTILINE)

_KIND_OK = re.compile(r"^[a-z][a-z0-9_]*$")


def _call_sites():
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        for m in _CALL.finditer(text):
            line = text[:m.start()].count("\n") + 1
            yield path.relative_to(PKG.parent), line, m.group(2), \
                m.group(4)


def test_call_sites_exist():
    sites = list(_call_sites())
    # The suite is vacuous if the grep regex rots; PR 11 alone
    # instruments dozens of sites.
    assert len(sites) > 30, f"grep found only {len(sites)} sites"


def test_planes_are_registered():
    bad = [(str(f), ln, pl, k) for f, ln, pl, k in _call_sites()
           if pl not in events.PLANES]
    assert not bad, f"unregistered plane strings: {bad}"


def test_kinds_are_snake_case():
    bad = [(str(f), ln, pl, k) for f, ln, pl, k in _call_sites()
           if not _KIND_OK.match(k)]
    assert not bad, f"malformed span/event kinds: {bad}"


def test_imperative_begins_have_ends():
    """A file using spans.begin() must also call spans.end() — the token
    API is imperative, so a file-local end is the only way a begin can
    ever close (the context form needs no end)."""
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        if "spans.begin(" in text and "spans.end(" not in text \
                and path.name != "spans.py":
            offenders.append(str(path.relative_to(PKG.parent)))
    assert not offenders, \
        f"files that begin spans but never end any: {offenders}"


def test_control_plane_span_kinds_present():
    """The batched control plane (PR 14) is attributable only because
    these spans exist: scale_attrib's actor_storm mode needs the spawn
    path (fork/boot), `cli analyze` needs gcs/flush, and the batched
    lease/dispatch path keeps the PR 11 per-task kinds.  Losing any of
    them silently blinds the attribution tooling, so pin them here."""
    sites = {(pl, k) for _, _, pl, k in _call_sites()}
    required = {
        ("sched", "zygote_fork"),   # hostd: batched fork via the zygote
        ("sched", "worker_boot"),   # hostd: fork -> worker_ready
        ("gcs", "flush"),           # gcs: coalesced write_rows commit
        ("sched", "lease_wait"),    # driver: one per (batched) lease RPC
        ("sched", "dispatch"),      # driver: still one per task
        ("sched", "inflight"),      # driver: shipped -> push completion
    }
    missing = required - sites
    assert not missing, f"control-plane span kinds vanished: {missing}"


def test_span_kinds_do_not_collide_with_instant_kinds():
    """One (plane, kind) must be either always-instant or always-span:
    build_breakdown keys phases by (plane, kind), so a mixed kind would
    split its statistics.  Known exceptions: none."""
    span_kinds, instant_kinds = set(), set()
    spans_call = re.compile(
        r"(spans\.begin|spans\.span|events\.record)\(\s*\n?\s*"
        r"(['\"])([^'\"]*)\2\s*,\s*\n?\s*(['\"])([^'\"]*)\4",
        re.MULTILINE)
    for path in sorted(PKG.rglob("*.py")):
        if path.name in ("spans.py", "events.py"):
            continue
        for m in spans_call.finditer(path.read_text()):
            key = (m.group(3), m.group(5))
            if m.group(1) == "events.record":
                instant_kinds.add(key)
            else:
                span_kinds.add(key)
    mixed = span_kinds & instant_kinds
    # serve/admit intentionally exists in both forms: the instant event
    # is the always-on SLO sample, the span only appears under a trace.
    mixed -= {("serve", "admit")}
    assert not mixed, f"(plane, kind) used as both span and instant: {mixed}"

def test_pp_span_kinds_present():
    """The MPMD pipeline trainer (PR 15) is attributable only because
    these spans exist: scale_attrib's pp mode derives the bubble
    fraction from the unattributed remainder of stage_fwd/stage_bwd/
    xfer/apply/ckpt/recover, and the chaos gates key on the stage_dead/
    replay/rollback instants.  Pin them so refactors cannot silently
    blind the tooling."""
    sites = {(pl, k) for _, _, pl, k in _call_sites()}
    required_spans = {
        ("pp", "stage_fwd"),    # stage actor: one microbatch forward
        ("pp", "stage_bwd"),    # stage actor: one microbatch backward
        ("pp", "xfer"),         # stage actor: BLOCKING inter-stage fetch
        ("pp", "xfer_overlap"),  # stage actor: prefetch-thread fetch,
                                 # concurrent with compute (PR 18)
        ("pp", "recv_wait"),    # stage actor: compute waits on an
                                # in-flight prefetch (exposed overlap)
        ("pp", "apply"),        # stage actor: fold partials + SGD update
        ("pp", "ckpt"),         # stage actor: per-stage sharded save
        ("pp", "step"),         # driver: whole pipeline step
        ("pp", "recover"),      # driver: reform/replay/rollback window
    }
    required_instants = {
        ("pp", "bubble"),       # stage actor: idle gap between ops
        ("pp", "stage_dead"),   # driver: a gang was declared dead
        ("pp", "replay"),       # driver: surgical in-place replay chosen
        ("pp", "rollback"),     # driver: global rollback chosen
        ("pp", "prepush"),      # driver: activation ref shipped into a
                                # downstream receive window
        ("pp", "placement"),    # driver: topology placement plan applied
    }
    missing = (required_spans | required_instants) - sites
    assert not missing, f"pp plane kinds vanished: {missing}"


def test_pp_compute_spans_are_chunk_tagged():
    """The interleaved schedule (PR 18) multiplexes several stage-chunks
    onto one gang; attribution and debugging need the chunk id on every
    compute/transfer span.  Pin the tag at the call sites so a refactor
    cannot silently collapse chunks back into an undifferentiated
    stage."""
    src = (PKG / "train" / "pipeline_stage.py").read_text()
    for kind in ("stage_fwd", "stage_bwd", "xfer", "xfer_overlap",
                 "recv_wait"):
        m = re.search(
            r'spans\.(?:span|begin)\(\s*"pp",\s*"%s",([^)]*)\)' % kind,
            src)
        assert m, f"pp/{kind} span call site not found"
        assert "chunk=" in m.group(1), \
            f"pp/{kind} span lost its chunk= tag"


def test_kv_plane_kinds_present():
    """The disaggregated-serving plane (serve/kv_tier) is attributable
    only through these kinds: scale_attrib's serve mode carves request
    wall into route/prefill/kv_xfer/decode via the spans, and the chaos
    gates + bench key on the tier/handoff instants.  Pin them so
    refactors cannot silently blind the tooling."""
    sites = {(pl, k) for _, _, pl, k in _call_sites()}
    required_spans = {
        ("kv", "export"),        # engine: gather sealed chain for handoff
        ("kv", "import"),        # engine: adopt a shipped chain
        ("kv", "handoff"),       # handle: prefill hop + frame transfer
    }
    required_instants = {
        ("kv", "spilled"),       # tier: block left the device pool
        ("kv", "restored"),      # tier: spilled block rejoined the pool
        ("kv", "dropped"),       # tier: block fell off the last tier
        ("kv", "handoff_lost"),  # handle: prefill died, decode re-prefills
        ("serve", "prefix_route"),  # router: prefix affinity won a pick
    }
    missing = (required_spans | required_instants) - sites
    assert not missing, f"kv plane kinds vanished: {missing}"


def test_rl_plane_kinds_present():
    """The Podracer actor/learner substrate (PR 20) is attributable only
    because these kinds exist: scale_attrib's rl mode carves wall into
    rollout/learn/publish/adopt via the spans, and the chaos gates +
    staleness accounting key on the instants.  Pin them so refactors
    cannot silently blind the tooling."""
    sites = {(pl, k) for _, _, pl, k in _call_sites()}
    required_spans = {
        ("rl", "publish"),        # driver: one put + gang-wide adopt fan-out
        ("rl", "adopt"),          # actor: in-place weight swap (live lanes)
        ("rl", "rollout"),        # actor: one versioned fragment/episode gang
        ("rl", "learn"),          # learner: one V-trace SGD step
    }
    required_instants = {
        ("rl", "stale_drop"),     # queue: batch beyond the staleness bound
        ("rl", "backpressure"),   # queue: producer held, queue full
        ("rl", "worker_replaced"),  # controller: rollout gang re-formed
        ("rl", "learner_resume"),   # learner: restored from COMMITTED ckpt
        ("engine", "weights_swap"),  # engine: params swapped between steps
    }
    missing = (required_spans | required_instants) - sites
    assert not missing, f"rl plane kinds vanished: {missing}"


def test_gcs_ft_event_kinds_present():
    """The head-survival plane (PR 16) is observable only through these
    instants: the availability bench and the chaos gates key on the
    kill/restore/fence records, and `cli events` surfaces outages via
    unreachable/reconnected.  Pin them so refactors cannot silently
    blind the recovery tooling."""
    sites = {(pl, k) for _, _, pl, k in _call_sites()}
    required = {
        ("gcs", "restored"),            # gcs: tables rebuilt from sqlite
        ("gcs", "node_fenced"),         # gcs: stale re-register refused
        ("gcs", "node_resync"),         # gcs: anti-entropy snapshot applied
        ("gcs", "chaos_kill"),          # gcs: scripted pre-request kill
        ("gcs", "chaos_kill_flush"),    # gcs: scripted mid-flush kill
        ("gcs", "supervisor_respawn"),  # launcher: head respawned in place
        ("gcs", "supervisor_gave_up"),  # launcher: restart budget spent
        ("gcs", "unreachable"),         # client/hostd: outage onset
        ("gcs", "reconnected"),         # client: outage over, duration
        ("link", "blackhole"),          # chaos: partition window opened
        ("link", "heal"),               # chaos: partition window closed
        ("proc", "node_fenced"),        # hostd: killed own stale workers
        ("proc", "stale_actor_reaped"), # hostd: one failed-over actor gone
        ("serve", "stale_routing"),     # router: served on cache in outage
    }
    missing = required - sites
    assert not missing, f"gcs-ft event kinds vanished: {missing}"
