"""Examples smoke shard: every committed example script must EXECUTE
(reference coverage model: the reference CI runs its doc examples;
README snippets that never run rot).  Run with `pytest -m examples`.

Each script is a standalone ray_tpu program (it calls init/shutdown
itself), so they run as subprocesses, serially, with a generous
timeout for the RL/train ones."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")
SCRIPTS = sorted(f for f in os.listdir(EXAMPLES) if f.endswith(".py"))


@pytest.mark.examples
@pytest.mark.slow
@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout[-3000:]}\n"
        f"--- stderr ---\n{proc.stderr[-3000:]}")
