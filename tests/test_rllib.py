"""RL stack tests: GAE/V-trace math, rollout workers, PPO learning to
target reward, IMPALA async smoke, fault tolerance, Tune integration.

Reference coverage model: rllib/tests/ + per-algorithm tests
(rllib/algorithms/ppo/tests/test_ppo.py learning sanity,
rllib/algorithms/impala/tests/) and the tuned_examples reward-threshold
regression pattern (reference: rllib/tuned_examples/ppo/cartpole-ppo.yaml —
episode_reward_mean >= 150 gate; we gate at the full 475 'solved' bar).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    IMPALAConfig,
    PPOConfig,
    RolloutWorker,
    SampleBatch,
    compute_gae,
    make_vector_env,
    register_env,
    vtrace,
)


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=128 << 20)
    yield info
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Math: GAE and V-trace
# ---------------------------------------------------------------------------


def test_gae_matches_direct_recursion():
    rng = np.random.default_rng(0)
    T, B = 12, 3
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = rng.random((T, B)) < 0.15
    bootstrap = rng.normal(size=B).astype(np.float32)
    gamma, lam = 0.97, 0.9

    adv, targets = compute_gae(rewards, values, dones, bootstrap, gamma, lam)

    # Direct per-env recursion.
    for b in range(B):
        gae = 0.0
        nv = bootstrap[b]
        for t in range(T - 1, -1, -1):
            nd = 0.0 if dones[t, b] else 1.0
            delta = rewards[t, b] + gamma * nv * nd - values[t, b]
            gae = delta + gamma * lam * nd * gae
            assert adv[t, b] == pytest.approx(gae, rel=1e-4, abs=1e-5)
            nv = values[t, b]
    np.testing.assert_allclose(targets, adv + values, rtol=1e-5)


def test_vtrace_on_policy_reduces_to_nstep_returns():
    """With behavior == target policy, rhos == cs == 1 and vs_t equals the
    discounted n-step return bootstrapped with V."""
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    T, B = 8, 2
    logp = rng.normal(size=(T, B)).astype(np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    bootstrap = rng.normal(size=B).astype(np.float32)
    discounts = np.full((T, B), 0.95, np.float32)

    out = vtrace(jnp.asarray(logp), jnp.asarray(logp), jnp.asarray(rewards),
                 jnp.asarray(discounts), jnp.asarray(values),
                 jnp.asarray(bootstrap))
    vs = np.asarray(out.vs)

    expected = np.empty_like(values)
    nxt = bootstrap.copy()
    for t in range(T - 1, -1, -1):
        expected[t] = rewards[t] + discounts[t] * nxt
        nxt = expected[t]
    np.testing.assert_allclose(vs, expected, rtol=1e-4, atol=1e-4)


def test_vtrace_rho_clipping_bounds_targets():
    """Extremely off-policy rhos are clipped: targets stay finite/bounded."""
    import jax.numpy as jnp

    T, B = 6, 2
    behavior = np.full((T, B), -20.0, np.float32)   # behavior logp tiny
    target = np.zeros((T, B), np.float32)           # target logp large
    rewards = np.ones((T, B), np.float32)
    values = np.zeros((T, B), np.float32)
    discounts = np.full((T, B), 0.99, np.float32)
    out = vtrace(jnp.asarray(behavior), jnp.asarray(target),
                 jnp.asarray(rewards), jnp.asarray(discounts),
                 jnp.asarray(values), jnp.zeros(B, jnp.float32),
                 clip_rho_threshold=1.0, clip_c_threshold=1.0)
    # With rho clipped to 1 this is exactly the on-policy return.
    assert float(np.max(np.abs(out.vs))) < 10.0


# ---------------------------------------------------------------------------
# Envs + rollout workers
# ---------------------------------------------------------------------------


def test_cartpole_vector_env_contract():
    env = make_vector_env("CartPole-v1", 4, seed=3)
    obs = env.reset_all(3)
    assert obs.shape == (4, 4) and obs.dtype == np.float32
    for _ in range(50):
        obs, rew, term, trunc = env.step(np.ones(4, np.int64))
        assert rew.shape == (4,)
    # Constant-action episodes terminate quickly; metrics must accumulate.
    rets, lens = env.drain_episode_metrics()
    assert len(rets) > 0 and all(r > 0 for r in rets)


def test_rollout_worker_batch_shapes_local():
    w = RolloutWorker(env="CartPole-v1", num_envs=4,
                      rollout_fragment_length=16, seed=0)
    batch, metrics = w.sample()
    assert batch.count == 64
    assert set(batch) >= {SampleBatch.OBS, SampleBatch.ACTIONS,
                          SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS,
                          SampleBatch.ADVANTAGES, SampleBatch.VALUE_TARGETS}
    assert metrics["env_steps"] == 64
    # Time-major (IMPALA) layout.
    w2 = RolloutWorker(env="CartPole-v1", num_envs=4,
                       rollout_fragment_length=16, seed=0, postprocess=False)
    tb, _ = w2.sample()
    assert tb[SampleBatch.OBS].shape == (16, 4, 4)
    assert tb["bootstrap_obs"].shape == (4, 4)


def test_custom_env_registration():
    class TrivialVec(make_vector_env("CartPole-v1", 1).__class__):
        pass

    register_env("Trivial-v0", lambda n, seed=0: TrivialVec(n, seed=seed))
    env = make_vector_env("Trivial-v0", 2, seed=0)
    assert env.num_envs == 2


# ---------------------------------------------------------------------------
# PPO: learning regression (the tuned_examples gate)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_ppo_cartpole_reaches_475(cluster):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=16,
                     rollout_fragment_length=64)
           .training(train_batch_size=4096, sgd_minibatch_size=256,
                     num_sgd_iter=10, lr=5e-4, entropy_coeff=0.005)
           .debugging(seed=1))
    algo = cfg.build()
    try:
        best = -np.inf
        for i in range(80):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if result["episode_reward_mean"] >= 475:
                break
        assert best >= 475, f"PPO failed to solve CartPole: best={best}"
        assert result["timesteps_total"] > 0
    finally:
        algo.stop()


def test_ppo_checkpoint_roundtrip(cluster):
    cfg = (PPOConfig().environment("CartPole-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                     rollout_fragment_length=16)
           .training(train_batch_size=64, sgd_minibatch_size=32,
                     num_sgd_iter=2))
    algo = cfg.build()
    algo.train()
    ckpt = algo.save()
    w_before = algo.learner.get_weights()

    algo2 = cfg.build()
    algo2.restore(ckpt)
    w_after = algo2.learner.get_weights()
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(w_before),
                    jax.tree_util.tree_leaves(w_after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert algo2.iteration == algo.iteration
    algo.stop()
    algo2.stop()


def test_worker_set_survives_worker_kill(cluster):
    cfg = (PPOConfig().environment("CartPole-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                     rollout_fragment_length=16)
           .training(train_batch_size=128, sgd_minibatch_size=64,
                     num_sgd_iter=2))
    algo = cfg.build()
    try:
        algo.train()
        ray_tpu.kill(algo.workers.remote_workers[0])
        # The next rounds must replace the dead worker and keep sampling.
        result = algo.train()
        assert result["sampled_rows"] >= 128
        assert algo.workers.num_remote_workers == 2
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# IMPALA: async actor-learner smoke
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_impala_smoke_learns_and_counts_updates(cluster):
    cfg = (IMPALAConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                     rollout_fragment_length=32)
           .training(lr=5e-4, entropy_coeff=0.01, min_updates_per_step=2)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        first = algo.train()
        assert first["learner_updates_total"] >= 2
        rewards = []
        for _ in range(35):
            r = algo.train()
            rewards.append(r["episode_reward_mean"])
            if rewards[-1] > 40:
                break
        # Async learner must keep consuming and reward should move off the
        # random-policy floor (~20 for CartPole).
        assert r["learner_updates_total"] >= 40
        assert max(rewards) > 40, f"IMPALA made no progress: {rewards}"
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# Tune integration: Algorithm as trainable
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_ppo_under_tune(cluster):
    from ray_tpu import tune
    from ray_tpu.rllib import PPO

    cfg = (PPOConfig().environment("CartPole-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                     rollout_fragment_length=16)
           .training(train_batch_size=64, sgd_minibatch_size=32,
                     num_sgd_iter=2))
    trainable = PPO.as_trainable(cfg, stop_iters=3)
    results = tune.run(trainable, config={"lr": tune.grid_search([1e-4, 5e-4])},
                       metric="episode_reward_mean", mode="max",
                       resources_per_trial={"CPU": 1})
    assert len(results) == 2
    assert not results.errors


@pytest.mark.slow
def test_a2c_learns_cartpole(cluster):
    """A2C (reference: rllib/algorithms/a2c) improves past the random
    floor with the shared sync-sample plumbing."""
    from ray_tpu.rllib import A2CConfig

    cfg = (A2CConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                     rollout_fragment_length=32)
           .training(train_batch_size=2048, lr=1e-3, entropy_coeff=0.005)
           .debugging(seed=3))
    algo = cfg.build()
    try:
        best = 0.0
        for _ in range(60):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > 50:
                break
        # Plain policy gradient is slow but must clear the ~22 random floor.
        assert best > 50, f"A2C made no progress: best={best}"
    finally:
        algo.stop()


def test_replay_buffers():
    """Uniform ring semantics + prioritized sampling weights (reference:
    rllib/utils/replay_buffers/)."""
    from ray_tpu.rllib import PrioritizedReplayBuffer, ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    for i in range(15):
        buf.add(SampleBatch({"x": np.full(10, i)}))
    assert len(buf) == 100  # ring wrapped (150 added)
    sample = buf.sample(32)
    assert sample["x"].shape == (32,)
    assert sample["x"].min() >= 5  # first 50 rows overwritten

    pbuf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, seed=0)
    pbuf.add(SampleBatch({"x": np.arange(64)}))
    # Crank priority of index 7: it must dominate samples.
    pbuf.update_priorities(np.array([7]), np.array([1000.0]))
    s = pbuf.sample(256, beta=0.4)
    assert (s["x"] == 7).mean() > 0.5
    assert s["weights"].max() == pytest.approx(1.0)


@pytest.mark.slow
def test_dqn_learns_cartpole(cluster):
    """DQN (reference: rllib/algorithms/dqn) with replay + target network
    + double-Q clears a CartPole learning gate."""
    from ray_tpu.rllib import DQNConfig

    cfg = (DQNConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                     rollout_fragment_length=32)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = 0.0
        for _ in range(120):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > 150:
                break
        assert best > 150, f"DQN made no progress: best={best}"
        assert r["buffer_size"] > 0
        assert r["learner_updates_total"] > 0
    finally:
        algo.stop()


def test_offline_io_and_behavior_cloning(cluster, tmp_path):
    """Experience JSON round-trip + BC recovers an expert policy from
    logged data (reference: rllib/offline json_writer/json_reader +
    algorithms/bc)."""
    from ray_tpu.rllib import BC, BCConfig, JsonReader, JsonWriter

    # Synthetic expert over diverse states: act 1 iff the pole leans
    # right (obs[2] > 0).
    rng = np.random.default_rng(0)
    writer = JsonWriter(str(tmp_path / "exp"))
    for _ in range(40):
        obs = rng.uniform(-0.2, 0.2, size=(16, 4)).astype(np.float32)
        actions = (obs[:, 2] > 0).astype(np.int64)
        writer.write(SampleBatch({SampleBatch.OBS: obs,
                                  SampleBatch.ACTIONS: actions}))
    writer.close()

    reader = JsonReader(str(tmp_path / "exp"))
    all_exp = reader.read_all()
    assert all_exp.count == 640
    assert all_exp[SampleBatch.OBS].shape == (640, 4)

    # Data integration: experiences load as a Dataset.
    ds = reader.to_dataset()
    assert ds.count() == 640

    bc = BC(obs_dim=4, num_actions=2, config=BCConfig())
    for _ in range(30):
        metrics = bc.train_on(all_exp)
    assert metrics["samples"] == 640
    # Cloned policy reproduces the expert rule on held-out states.
    test_obs = rng.uniform(-0.2, 0.2, size=(200, 4)).astype(np.float32)
    pred = bc.compute_actions(test_obs)
    expert = (test_obs[:, 2] > 0).astype(np.int64)
    assert (pred == expert).mean() > 0.95


@pytest.mark.slow
def test_ppo_continuous_pendulum(cluster):
    """Continuous control: Gaussian-policy PPO improves Pendulum swing-up
    well past the random floor (~-1250) (reference: PPO over DiagGaussian
    action distributions; rllib/tuned_examples/ppo/pendulum-ppo.yaml)."""
    cfg = (PPOConfig()
           .environment("Pendulum-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                     rollout_fragment_length=128)
           .training(train_batch_size=4096, sgd_minibatch_size=512,
                     num_sgd_iter=10, lr=1e-3, entropy_coeff=0.0,
                     clip_param=0.2, vf_clip_param=1e6, gamma=0.95,
                     grad_clip=1.0)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = -np.inf
        for _ in range(150):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > -400:
                break
        assert best > -400, f"continuous PPO made no progress: {best}"
        # Action plumbing sanity: continuous batches carry float actions.
        batch, _ = algo.workers.local_worker.sample()
        assert batch[SampleBatch.ACTIONS].dtype == np.float32
        assert batch[SampleBatch.ACTIONS].shape[-1] == 1
    finally:
        algo.stop()


@pytest.mark.slow
def test_sac_learns_pendulum(cluster):
    """Continuous off-policy: SAC (twin soft Q + squashed-Gaussian actor +
    entropy autotuning) solves Pendulum swing-up well past the random
    floor (reference: rllib/algorithms/sac)."""
    from ray_tpu.rllib import SACConfig
    cfg = (SACConfig()
           .environment("Pendulum-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                     rollout_fragment_length=32)
           .training(updates_per_step=256)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = -np.inf
        for _ in range(70):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > -450:
                break
        assert best > -450, f"SAC made no progress: {best}"
        # alpha is autotuned downward from 1.0 as the policy sharpens
        assert r["learner/alpha"] < 0.9
        # checkpoint roundtrip keeps the learned actor
        ckpt = algo.save()
        algo.restore(ckpt)
        r2 = algo.train()
        assert r2["episode_reward_mean"] > -600
    finally:
        algo.stop()


@pytest.mark.slow
def test_td3_learns_pendulum(cluster):
    """Continuous off-policy: TD3 (twin Q + delayed deterministic policy +
    target smoothing) improves Pendulum well past the random floor
    (reference: rllib/algorithms/td3)."""
    from ray_tpu.rllib import TD3Config
    cfg = (TD3Config()
           .environment("Pendulum-v1")
           .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                     rollout_fragment_length=32)
           .training(updates_per_step=256)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = -np.inf
        for _ in range(70):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > -500:
                break
        assert best > -500, f"TD3 made no progress: {best}"
    finally:
        algo.stop()


def test_sac_remote_rollout_plumbing(cluster):
    """SAC's squashed-Gaussian behavior policy works on REMOTE rollout
    actors (policy_kind plumbed through worker_kwargs; weight broadcast
    format matches the actor network)."""
    from ray_tpu.rllib import SACConfig
    cfg = (SACConfig()
           .environment("Pendulum-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                     rollout_fragment_length=16)
           .training(learning_starts=64, updates_per_step=2)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        r1 = algo.train()
        r2 = algo.train()
        assert r2["buffer_size"] > r1["buffer_size"] > 0
        assert r2["learner_updates_total"] > 0
    finally:
        algo.stop()


def test_conv_model_forward_shapes():
    """Nature-CNN actor-critic on [84,84,4] frames (reference:
    ModelCatalog vision_net; VERDICT r2 item 8)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import make_model

    init, apply = make_model((84, 84, 4), 4)
    params = init(jax.random.key(0))
    obs = jnp.zeros((3, 84, 84, 4), jnp.uint8)
    logits, value = apply(params, obs)
    assert logits.shape == (3, 4) and value.shape == (3,)


def test_pixel_env_uint8_pipeline():
    """The synthetic Atari-shaped env keeps uint8 end to end through the
    rollout buffers (pixels move at 1 byte each)."""
    import numpy as np

    from ray_tpu.rllib.rollout_worker import RolloutWorker

    w = RolloutWorker("SyntheticPixel-v0", num_envs=2,
                      rollout_fragment_length=4, postprocess=False)
    batch, metrics = w.sample()
    assert batch["obs"].shape == (4, 2, 84, 84, 4)
    assert batch["obs"].dtype == np.uint8
    assert batch["action_logits"].shape == (4, 2, 4)


@pytest.mark.slow
def test_impala_pixel_throughput(cluster):
    """IMPALA on the pixel env: async rollouts feed the conv V-trace
    learner; gate on env-steps/sec progress (not reward — the reference's
    Atari yamls gate throughput in release tests)."""
    import time

    from ray_tpu.rllib.impala import IMPALAConfig

    cfg = (IMPALAConfig()
           .environment("SyntheticPixel-v0")
           .rollouts(num_rollout_workers=1, num_envs_per_worker=4,
                     rollout_fragment_length=8)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        algo.train()  # compile + spawn
        t0 = time.perf_counter()
        s0, u0 = algo.total_env_steps, algo.learner.num_updates
        while time.perf_counter() - t0 < 10.0:
            algo.train()
        dt = time.perf_counter() - t0
        rate = (algo.total_env_steps - s0) / dt
        updates = algo.learner.num_updates - u0
        print(f"pixel IMPALA: {rate:,.0f} env-steps/s, "
              f"{updates/dt:.1f} updates/s")
        assert updates >= 3, "learner thread made no progress"
        assert rate > 50, f"pixel pipeline too slow: {rate:.0f} steps/s"
    finally:
        algo.stop()


@pytest.mark.slow
def test_appo_learns_cartpole(cluster):
    """APPO (reference: rllib/algorithms/appo) — IMPALA's async pipeline
    with PPO's clipped surrogate on V-trace advantages; smoke gate like
    IMPALA's: clear learning within a bounded budget."""
    from ray_tpu.rllib.appo import APPOConfig

    cfg = (APPOConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=1, num_envs_per_worker=8,
                     rollout_fragment_length=32)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = 0.0
        for _ in range(40):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best >= 50.0:
                break
        assert best >= 50.0, f"APPO failed to learn: best={best}"
        assert algo.learner.num_updates > 0
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# Recurrent (LSTM) policies — reference: rllib/models/torch/recurrent_net.py
# + rnn_sequencing.py.  RepeatPrev-v0 rewards emitting the PREVIOUS step's
# symbol: zero-information current obs, so feedforward is capped at chance
# while one step of memory solves it — the separation the gate asserts.
# ---------------------------------------------------------------------------


def test_recurrent_model_seq_matches_steps_and_resets():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import make_recurrent_model
    init, step, seq, init_state = make_recurrent_model(3, 3, (16,), 8)
    p = init(jax.random.key(0))
    obs = jax.random.normal(jax.random.key(1), (6, 4, 3))
    s0 = jnp.asarray(init_state(4))
    # No resets: scanning == iterating single steps.
    logits_seq, values_seq = seq(p, obs, s0, jnp.zeros((6, 4), bool))
    s = s0
    for t in range(6):
        lg, vv, s = step(p, obs[t], s)
        assert np.allclose(lg, logits_seq[t], atol=1e-5)
        assert np.allclose(vv, values_seq[t], atol=1e-5)
    # A reset at t=3 makes outputs from t=3 match a fresh-state run of
    # the suffix — the masked carry IS the episode boundary.
    resets = jnp.zeros((6, 4), bool).at[3].set(True)
    logits_r, _ = seq(p, obs, s0, resets)
    logits_fresh, _ = seq(p, obs[3:], s0, jnp.zeros((3, 4), bool))
    assert np.allclose(logits_r[3:], logits_fresh, atol=1e-5)
    assert not np.allclose(logits_r[3], logits_seq[3], atol=1e-3)


def test_recurrent_rollout_batch_layout():
    w = RolloutWorker("RepeatPrev-v0", num_envs=4,
                      rollout_fragment_length=8,
                      policy_kind="recurrent", lstm_size=8, hidden=(16,),
                      seed=0)
    b, m = w.sample()
    assert b[SampleBatch.OBS].shape == (4, 8, 3)        # [B, T, D]
    assert b["resets"].shape == (4, 8)
    assert b["state_in"].shape == (4, 2, 8)             # [B, 2, H]
    assert m["env_steps"] == 32


@pytest.mark.slow
def test_recurrent_ppo_solves_memory_task_feedforward_cannot():
    """LSTM reaches near-perfect return on RepeatPrev while an identical
    feedforward budget stays at chance (~16.6 of 48) — the capability
    axis a recurrent policy adds (reference: the LSTM examples gate on
    RepeatAfterMeEnv)."""
    from ray_tpu.rllib.learner import (
        JaxLearner,
        ppo_loss,
        ppo_loss_recurrent,
    )

    def train(recurrent: bool):
        kw = (dict(policy_kind="recurrent", lstm_size=32)
              if recurrent else {})
        w = RolloutWorker("RepeatPrev-v0", num_envs=32,
                          rollout_fragment_length=24, hidden=(32,),
                          seed=0, gamma=0.5, lam=0.9, **kw)
        ln = JaxLearner(
            3, 3, hidden=(32,),
            model=("lstm" if recurrent else "fc"), lstm_size=32,
            loss_fn=(ppo_loss_recurrent if recurrent else ppo_loss),
            config={"lr": 5e-3, "num_sgd_iter": 8,
                    "sgd_minibatch_size": 16 if recurrent else 256,
                    "entropy_coeff": 0.01})
        for _ in range(120):
            w.set_weights(ln.get_weights())
            b, _m = w.sample()
            ln.update(b)
        rets = []
        for _ in range(4):
            _b, m = w.sample()
            rets += m["episode_returns"]
        return sum(rets) / max(len(rets), 1)

    lstm_ret = train(recurrent=True)
    assert lstm_ret > 40, f"recurrent policy failed the memory task: " \
                          f"{lstm_ret:.1f}/48"
    ff_ret = train(recurrent=False)
    assert ff_ret < 26, f"feedforward should be chance-capped: " \
                        f"{ff_ret:.1f}/48"


@pytest.mark.slow
def test_recurrent_ppo_and_impala_through_algorithm(cluster):
    """The use_lstm switch plumbs end-to-end through both Algorithm
    classes: one PPO train step and one IMPALA train step run with
    finite losses and recurrent batch columns."""
    cfg = PPOConfig().environment("RepeatPrev-v0")
    cfg.num_rollout_workers = 1
    cfg.num_envs_per_worker = 8
    cfg.rollout_fragment_length = 16
    cfg.train_batch_size = 8          # sequences
    cfg.sgd_minibatch_size = 8
    cfg.num_sgd_iter = 2
    cfg.use_lstm = True
    cfg.lstm_size = 16
    cfg.model_hidden = (16,)
    algo = cfg.build()
    r = algo.train()
    assert np.isfinite(r["learner/total_loss"])
    algo.stop()

    icfg = IMPALAConfig().environment("RepeatPrev-v0")
    icfg.num_rollout_workers = 1
    icfg.num_envs_per_worker = 8
    icfg.rollout_fragment_length = 16
    icfg.use_lstm = True
    icfg.lstm_size = 16
    icfg.model_hidden = (16,)
    ialgo = icfg.build()
    r = ialgo.train()
    assert np.isfinite(r.get("learner/total_loss", 0.0))
    ialgo.stop()


def test_bc_trains_from_parquet_dataset(cluster, tmp_path):
    """The Data-native offline path (reference: offline/dataset_reader.py):
    experiences written as Parquet by the experience writer, read back
    through ray_tpu.data with parallel block reads, STREAMED into BC in
    minibatches — the cloned policy recovers the expert rule."""
    from ray_tpu.rllib import BC, BCConfig
    from ray_tpu.rllib.offline import DatasetReader, ParquetWriter

    rng = np.random.default_rng(1)
    writer = ParquetWriter(str(tmp_path / "pexp"))
    for _ in range(10):
        obs = rng.uniform(-0.2, 0.2, size=(64, 4)).astype(np.float32)
        actions = (obs[:, 2] > 0).astype(np.int64)
        writer.write(SampleBatch({SampleBatch.OBS: obs,
                                  SampleBatch.ACTIONS: actions}))
    writer.close()

    reader = DatasetReader.from_path(str(tmp_path / "pexp"),
                                     batch_size=128)
    bc = BC(obs_dim=4, num_actions=2, config=BCConfig())
    for _epoch in range(15):
        for minibatch in reader:       # streaming: never materializes all
            assert minibatch.count <= 128
            metrics = bc.train_on(minibatch)
    assert metrics["samples"] <= 128
    test_obs = rng.uniform(-0.2, 0.2, size=(200, 4)).astype(np.float32)
    pred = bc.compute_actions(test_obs)
    expert = (test_obs[:, 2] > 0).astype(np.int64)
    assert (pred == expert).mean() > 0.95


def test_es_centered_ranks_and_seed_noise():
    """ES primitives: centered ranks span [-0.5, 0.5] order-correctly and
    seed-coded perturbations are bit-identical across processes (the
    reference's shared noise table collapsed to a seed)."""
    import numpy as np

    from ray_tpu.rllib.es import centered_ranks

    x = np.array([3.0, -1.0, 10.0, 0.0])
    r = centered_ranks(x)
    assert r.min() == -0.5 and r.max() == 0.5
    assert r[x.argsort()].tolist() == sorted(r.tolist())
    e1 = np.random.default_rng(12345).standard_normal(64).astype(np.float32)
    e2 = np.random.default_rng(12345).standard_normal(64).astype(np.float32)
    assert (e1 == e2).all()


@pytest.mark.slow
def test_es_learns_cartpole(cluster):
    """ES (reference: rllib/algorithms/es) must solve CartPole via pure
    evolution — no gradients through the policy; the whole perturbation
    population evaluates as one vmapped rollout per worker."""
    from ray_tpu.rllib import ESConfig

    cfg = ESConfig().environment("CartPole-v1").rollouts(
        num_rollout_workers=2).debugging(seed=0)
    cfg.episodes_per_batch = 24
    cfg.episode_horizon = 300
    cfg.noise_stdev = 0.08
    cfg.lr = 0.05
    algo = cfg.build()
    try:
        best = 0.0
        for _ in range(30):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > 150:
                break
        assert best > 150, f"ES made no progress: best={best}"
        # Checkpoint round trip preserves the learned vector.
        ckpt = algo.save()
        theta = algo.theta.copy()
        algo.restore(ckpt)
        assert (algo.theta == theta).all()
    finally:
        algo.stop()


@pytest.mark.slow
def test_ars_learns_cartpole(cluster):
    """ARS (reference: rllib/algorithms/ars): top-direction selection +
    sigma_R normalization + the V2 observation filter must solve
    CartPole with a single-hidden-layer policy."""
    from ray_tpu.rllib import ARSConfig

    cfg = ARSConfig().environment("CartPole-v1").rollouts(
        num_rollout_workers=2).debugging(seed=1)
    cfg.episodes_per_batch = 16
    cfg.top_directions = 8
    cfg.episode_horizon = 300
    cfg.noise_stdev = 0.1
    cfg.lr = 0.05
    algo = cfg.build()
    try:
        best = 0.0
        for _ in range(35):
            r = algo.train()
            best = max(best, r["episode_reward_mean"])
            if best > 150:
                break
        assert best > 150, f"ARS made no progress: best={best}"
        # The V2 filter accumulated real observation moments.
        assert algo._obs_n > 1000
    finally:
        algo.stop()


def test_linucb_near_oracle_regret():
    """LinUCB (reference: rllib/algorithms/bandit/bandit_linucb.py) on a
    linear contextual bandit: per-decision reward must approach the
    context-dependent oracle and crush a random policy."""
    import numpy as np

    from ray_tpu.rllib import LinUCBConfig

    cfg = LinUCBConfig()
    cfg.seed = 7
    algo = cfg.build()
    try:
        for _ in range(15):
            r = algo.train()
        env = algo.env
        # Oracle/random comparison on fresh contexts via the env oracle.
        oracle, rnd, mine = [], [], []
        rng = np.random.default_rng(0)
        for _ in range(50):
            exp = env.expected_rewards()
            oracle.append(exp.max(-1).mean())
            rnd.append(exp.mean())
            arms = algo.compute_actions(algo._obs)
            mine.append(exp[np.arange(exp.shape[0]), arms].mean())
            algo._obs, _, _, _ = env.step(arms)
        oracle_m, rnd_m, mine_m = map(np.mean, (oracle, rnd, mine))
        assert mine_m > rnd_m + 0.7 * (oracle_m - rnd_m), \
            (mine_m, rnd_m, oracle_m)
        # Model survives a checkpoint round trip.
        ckpt = algo.save()
        before = algo.model.theta().copy()
        algo.restore(ckpt)
        assert np.allclose(algo.model.theta(), before)
    finally:
        algo.stop()


def test_lints_learns_posterior():
    """LinTS posterior sampling must also reach near-oracle decisions
    (exploration via posterior width, not a UCB bonus)."""
    import numpy as np

    from ray_tpu.rllib import LinTSConfig

    cfg = LinTSConfig()
    cfg.seed = 11
    algo = cfg.build()
    try:
        for _ in range(15):
            algo.train()
        env = algo.env
        oracle, rnd, mine = [], [], []
        for _ in range(50):
            exp = env.expected_rewards()
            oracle.append(exp.max(-1).mean())
            rnd.append(exp.mean())
            arms = algo.compute_actions(algo._obs)
            mine.append(exp[np.arange(exp.shape[0]), arms].mean())
            algo._obs, _, _, _ = env.step(arms)
        oracle_m, rnd_m, mine_m = map(np.mean, (oracle, rnd, mine))
        assert mine_m > rnd_m + 0.6 * (oracle_m - rnd_m), \
            (mine_m, rnd_m, oracle_m)
    finally:
        algo.stop()
