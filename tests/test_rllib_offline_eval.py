"""Off-policy estimator tests against closed-form values.

Reference coverage model: rllib/offline/estimators/tests/test_ope.py —
estimates on an enumerable MDP checked against hand-computed truth.

The MDP: start s0, horizon 2, s0 -> s1 always.  r(s0, a) = a;
r(s1, a) = 2 if a == 0 else 5.  Behavior uniform; target pi(s0) =
(0.2, 0.8), pi(s1) = (0.7, 0.3).  Feeding the estimator EVERY behavior
trajectory exactly once (each has probability 1/4) makes the empirical
batch average equal the estimator's EXPECTATION — so unbiased
estimators must hit the true target value exactly.
"""

import numpy as np
import pytest

from ray_tpu.rllib.estimators import (
    ESTIMATORS,
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    WeightedImportanceSampling,
    fit_fqe,
    split_episodes,
)
from ray_tpu.rllib.sample_batch import SampleBatch

GAMMA = 0.9
OBS = {0: [1.0, 0.0], 1: [0.0, 1.0]}
PI = {0: np.array([0.2, 0.8]), 1: np.array([0.7, 0.3])}
R_S1 = {0: 2.0, 1: 5.0}

V_S1 = 0.7 * 2 + 0.3 * 5                      # 2.9
V_TRUE = (0.2 * 0 + 0.8 * 1) + GAMMA * V_S1   # 0.8 + 2.61
V_BEHAVIOR = 0.5 + GAMMA * 3.5


def _enumerated_batch() -> SampleBatch:
    rows = {k: [] for k in ("obs", "actions", "rewards", "logp",
                            "term", "trunc")}
    for a0 in (0, 1):
        for a1 in (0, 1):
            for s, a, r, last in ((0, a0, float(a0), False),
                                  (1, a1, R_S1[a1], True)):
                rows["obs"].append(OBS[s])
                rows["actions"].append(a)
                rows["rewards"].append(r)
                rows["logp"].append(np.log(0.5))
                rows["term"].append(last)
                rows["trunc"].append(False)
    return SampleBatch({
        SampleBatch.OBS: np.array(rows["obs"], np.float32),
        SampleBatch.ACTIONS: np.array(rows["actions"], np.int64),
        SampleBatch.REWARDS: np.array(rows["rewards"], np.float32),
        SampleBatch.ACTION_LOGP: np.array(rows["logp"], np.float32),
        SampleBatch.TERMINATEDS: np.array(rows["term"], bool),
        SampleBatch.TRUNCATEDS: np.array(rows["trunc"], bool),
    })


def _target_probs(obs):
    return np.where(np.asarray(obs)[:, :1] == 1.0, PI[0], PI[1])


def _exact_q(obs):
    # Q^pi: Q(s1, a) = r(s1, a); Q(s0, a) = a + gamma * V(s1).
    q_s0 = np.array([0.0 + GAMMA * V_S1, 1.0 + GAMMA * V_S1])
    q_s1 = np.array([2.0, 5.0])
    return np.where(np.asarray(obs)[:, :1] == 1.0, q_s0, q_s1)


def test_split_episodes():
    eps = split_episodes(_enumerated_batch())
    assert len(eps) == 4
    assert all(len(e[SampleBatch.REWARDS]) == 2 for e in eps)


@pytest.mark.parametrize("cls", [ImportanceSampling,
                                 WeightedImportanceSampling])
def test_is_wis_match_closed_form(cls):
    est = cls(_target_probs, gamma=GAMMA)
    out = est.estimate(_enumerated_batch())
    assert out["episodes"] == 4
    assert abs(out["v_behavior"] - V_BEHAVIOR) < 1e-5
    # The enumerated batch IS the behavior expectation, and on it the
    # WIS normalization constants are exactly 1, so both are exact.
    assert abs(out["v_target"] - V_TRUE) < 1e-5, out


def test_dm_dr_with_exact_model_match_closed_form():
    for cls in (DirectMethod, DoublyRobust):
        est = cls(_target_probs, gamma=GAMMA, q_fn=_exact_q)
        out = est.estimate(_enumerated_batch())
        assert abs(out["v_target"] - V_TRUE) < 1e-5, (cls.__name__, out)


def test_dr_robust_to_wrong_model():
    """DR stays exact under a WRONG Q-model as long as the ratios are
    right (the doubly-robust property, averaged over the enumerated
    behavior distribution)."""
    bad_q = lambda obs: _exact_q(obs) + 1.7   # uniformly biased model
    est = DoublyRobust(_target_probs, gamma=GAMMA, q_fn=bad_q)
    out = est.estimate(_enumerated_batch())
    assert abs(out["v_target"] - V_TRUE) < 1e-5, out


def test_fqe_feeds_dm_close_to_truth():
    batch = SampleBatch.concat_samples([_enumerated_batch()] * 16)
    q_fn = fit_fqe(batch, _target_probs, num_actions=2, gamma=GAMMA,
                   iterations=400, lr=3e-2, hidden=(32,), seed=0)
    est = DirectMethod(_target_probs, gamma=GAMMA, q_fn=q_fn)
    out = est.estimate(batch)
    assert abs(out["v_target"] - V_TRUE) < 0.4, out


def test_estimator_registry():
    assert set(ESTIMATORS) == {"is", "wis", "dm", "dr"}
