"""Off-policy estimator tests against closed-form values.

Reference coverage model: rllib/offline/estimators/tests/test_ope.py —
estimates on an enumerable MDP checked against hand-computed truth.

The MDP: start s0, horizon 2, s0 -> s1 always.  r(s0, a) = a;
r(s1, a) = 2 if a == 0 else 5.  Behavior uniform; target pi(s0) =
(0.2, 0.8), pi(s1) = (0.7, 0.3).  Feeding the estimator EVERY behavior
trajectory exactly once (each has probability 1/4) makes the empirical
batch average equal the estimator's EXPECTATION — so unbiased
estimators must hit the true target value exactly.
"""

import numpy as np
import pytest

from ray_tpu.rllib.estimators import (
    ESTIMATORS,
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    WeightedImportanceSampling,
    fit_fqe,
    split_episodes,
)
from ray_tpu.rllib.sample_batch import SampleBatch

GAMMA = 0.9
OBS = {0: [1.0, 0.0], 1: [0.0, 1.0]}
PI = {0: np.array([0.2, 0.8]), 1: np.array([0.7, 0.3])}
R_S1 = {0: 2.0, 1: 5.0}

V_S1 = 0.7 * 2 + 0.3 * 5                      # 2.9
V_TRUE = (0.2 * 0 + 0.8 * 1) + GAMMA * V_S1   # 0.8 + 2.61
V_BEHAVIOR = 0.5 + GAMMA * 3.5


def _enumerated_batch() -> SampleBatch:
    rows = {k: [] for k in ("obs", "actions", "rewards", "logp",
                            "term", "trunc")}
    for a0 in (0, 1):
        for a1 in (0, 1):
            for s, a, r, last in ((0, a0, float(a0), False),
                                  (1, a1, R_S1[a1], True)):
                rows["obs"].append(OBS[s])
                rows["actions"].append(a)
                rows["rewards"].append(r)
                rows["logp"].append(np.log(0.5))
                rows["term"].append(last)
                rows["trunc"].append(False)
    return SampleBatch({
        SampleBatch.OBS: np.array(rows["obs"], np.float32),
        SampleBatch.ACTIONS: np.array(rows["actions"], np.int64),
        SampleBatch.REWARDS: np.array(rows["rewards"], np.float32),
        SampleBatch.ACTION_LOGP: np.array(rows["logp"], np.float32),
        SampleBatch.TERMINATEDS: np.array(rows["term"], bool),
        SampleBatch.TRUNCATEDS: np.array(rows["trunc"], bool),
    })


def _target_probs(obs):
    return np.where(np.asarray(obs)[:, :1] == 1.0, PI[0], PI[1])


def _exact_q(obs):
    # Q^pi: Q(s1, a) = r(s1, a); Q(s0, a) = a + gamma * V(s1).
    q_s0 = np.array([0.0 + GAMMA * V_S1, 1.0 + GAMMA * V_S1])
    q_s1 = np.array([2.0, 5.0])
    return np.where(np.asarray(obs)[:, :1] == 1.0, q_s0, q_s1)


def test_split_episodes():
    eps = split_episodes(_enumerated_batch())
    assert len(eps) == 4
    assert all(len(e[SampleBatch.REWARDS]) == 2 for e in eps)


@pytest.mark.parametrize("cls", [ImportanceSampling,
                                 WeightedImportanceSampling])
def test_is_wis_match_closed_form(cls):
    est = cls(_target_probs, gamma=GAMMA)
    out = est.estimate(_enumerated_batch())
    assert out["episodes"] == 4
    assert abs(out["v_behavior"] - V_BEHAVIOR) < 1e-5
    # The enumerated batch IS the behavior expectation, and on it the
    # WIS normalization constants are exactly 1, so both are exact.
    assert abs(out["v_target"] - V_TRUE) < 1e-5, out


def test_dm_dr_with_exact_model_match_closed_form():
    for cls in (DirectMethod, DoublyRobust):
        est = cls(_target_probs, gamma=GAMMA, q_fn=_exact_q)
        out = est.estimate(_enumerated_batch())
        assert abs(out["v_target"] - V_TRUE) < 1e-5, (cls.__name__, out)


def test_dr_robust_to_wrong_model():
    """DR stays exact under a WRONG Q-model as long as the ratios are
    right (the doubly-robust property, averaged over the enumerated
    behavior distribution)."""
    bad_q = lambda obs: _exact_q(obs) + 1.7   # uniformly biased model
    est = DoublyRobust(_target_probs, gamma=GAMMA, q_fn=bad_q)
    out = est.estimate(_enumerated_batch())
    assert abs(out["v_target"] - V_TRUE) < 1e-5, out


def test_fqe_feeds_dm_close_to_truth():
    batch = SampleBatch.concat_samples([_enumerated_batch()] * 16)
    q_fn = fit_fqe(batch, _target_probs, num_actions=2, gamma=GAMMA,
                   iterations=400, lr=3e-2, hidden=(32,), seed=0)
    est = DirectMethod(_target_probs, gamma=GAMMA, q_fn=q_fn)
    out = est.estimate(batch)
    assert abs(out["v_target"] - V_TRUE) < 0.4, out


def test_estimator_registry():
    assert set(ESTIMATORS) == {"is", "wis", "dm", "dr"}


# ---------------------------------------------------------------------------
# Offline LEARNING beyond BC (reference: rllib/algorithms/marwil, cql)
# ---------------------------------------------------------------------------


def _mixed_quality_log(n_episodes=60, ep_len=10, seed=0):
    """40% expert episodes (correct action, reward 1/step) and 60%
    anti-expert episodes (WRONG action, reward 0): majority-vote
    imitation (BC) learns the wrong action; only return weighting
    recovers the expert."""
    rng = np.random.default_rng(seed)
    obs, acts, rews, terms = [], [], [], []
    for ep in range(n_episodes):
        expert = ep % 5 < 2
        for t in range(ep_len):
            s = rng.uniform(-1, 1, 4).astype(np.float32)
            correct = int(s[2] > 0)
            a = correct if expert else 1 - correct
            obs.append(s)
            acts.append(a)
            rews.append(1.0 if expert else 0.0)
            terms.append(t == ep_len - 1)
    return SampleBatch({
        SampleBatch.OBS: np.stack(obs),
        SampleBatch.ACTIONS: np.array(acts, np.int64),
        SampleBatch.REWARDS: np.array(rews, np.float32),
        SampleBatch.TERMINATEDS: np.array(terms),
    })


def test_marwil_upweights_high_advantage_actions():
    """MARWIL's exp(beta*A/c) weight must pull the policy toward the
    HIGH-RETURN half of a mixed-quality log, beating BC (= beta 0) on
    expert-action agreement (reference: rllib/algorithms/marwil)."""
    from ray_tpu.rllib import MARWIL, MARWILConfig

    batch = _mixed_quality_log()
    rng = np.random.default_rng(42)
    test_obs = rng.uniform(-1, 1, (400, 4)).astype(np.float32)
    expert_actions = (test_obs[:, 2] > 0).astype(np.int64)

    def agreement(beta):
        cfg = MARWILConfig()
        cfg.beta = beta
        cfg.num_epochs = 40
        cfg.seed = 5
        algo = MARWIL(4, 2, cfg)
        algo.train_on(batch)
        return (algo.compute_actions(test_obs) == expert_actions).mean()

    bc_acc = agreement(0.0)       # plain BC: majority vote -> anti-expert
    marwil_acc = agreement(2.0)   # advantage-weighted -> expert
    assert bc_acc < 0.5, (marwil_acc, bc_acc)
    assert marwil_acc > 0.9, (marwil_acc, bc_acc)


def test_cql_conservative_on_out_of_support_actions():
    """Discrete CQL (reference: rllib/algorithms/cql — the logsumexp
    regularizer): on a 2-state MDP whose log NEVER takes action 2, CQL
    must (a) rank the logged-best action first and (b) push the unlogged
    action's Q below every logged action's, which plain TD does not
    guarantee."""
    from ray_tpu.rllib import CQL, CQLConfig

    rng = np.random.default_rng(3)
    n = 600
    s0 = np.eye(2, dtype=np.float32)[0]
    obs = np.tile(s0, (n, 1))
    acts = rng.integers(0, 2, n)           # only actions 0 and 1 logged
    rews = np.where(acts == 0, 1.0, 0.2).astype(np.float32)
    batch = SampleBatch({
        SampleBatch.OBS: obs,
        SampleBatch.ACTIONS: acts.astype(np.int64),
        SampleBatch.REWARDS: rews,
        SampleBatch.TERMINATEDS: np.ones(n, bool),  # bandit-style MDP
    })

    def train(alpha):
        cfg = CQLConfig()
        cfg.cql_alpha = alpha
        cfg.num_epochs = 30
        cfg.seed = 7
        algo = CQL(2, 3, cfg)
        algo.train_on(batch)
        return algo

    cql = train(1.0)
    q = cql.q_values(s0[None, :])[0]
    assert q.argmax() == 0, q                     # best logged action
    assert q[2] < q[1] < q[0], q                  # OOD action pushed DOWN
    # Conservatism is the regularizer's doing: with alpha=0 the OOD gap
    # (logged max minus Q of the never-taken action) must be smaller.
    td = train(0.0)
    q_td = td.q_values(s0[None, :])[0]
    assert (q.max() - q[2]) > (q_td.max() - q_td[2]) + 0.2, (q, q_td)
