"""Tests for the parallel layer (mesh/sharding/pipeline), ops (flash/ring
attention), and the flagship GPT model under DP/FSDP/TP/SP/EP shardings on
the 8-device CPU mesh (stand-in for an 8-chip slice; see conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import gpt
from ray_tpu.ops import flash_attention, reference_attention, ring_attention
from ray_tpu.parallel import (
    MeshConfig, create_mesh, logical_to_spec, pipeline_apply,
    shard_batch, stack_stage_params, tree_shardings)


def test_mesh_resolve():
    cfg = MeshConfig(data=-1, tensor=2)
    sizes = cfg.resolve(8)
    assert sizes["data"] == 4 and sizes["tensor"] == 2
    with pytest.raises(ValueError):
        MeshConfig(data=3, tensor=2).resolve(8)


def test_create_mesh_and_specs():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    assert mesh.shape["data"] == 2
    spec = logical_to_spec(("batch", "length", "embed"), mesh=mesh)
    # batch claims (data, fsdp); embed's fsdp is then dropped — a mesh axis
    # may shard at most one dim of a single array.
    assert spec == jax.sharding.PartitionSpec(("data", "fsdp"))
    spec = logical_to_spec(("embed", "mlp"), mesh=mesh)
    assert spec == jax.sharding.PartitionSpec("fsdp", "tensor")
    # Axes of size 1 are dropped.
    spec = logical_to_spec(("batch", "length"), mesh=mesh)
    assert spec == jax.sharding.PartitionSpec(("data", "fsdp"))


def test_flash_attention_matches_reference():
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(k1, (2, 256, 4, 64))
    k = jax.random.normal(k2, (2, 256, 4, 64))
    v = jax.random.normal(k3, (2, 256, 4, 64))
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_non_causal():
    k1, k2 = jax.random.split(jax.random.key(1))
    q = jax.random.normal(k1, (1, 128, 2, 64))
    kv = jax.random.normal(k2, (1, 128, 2, 64))
    out = flash_attention(q, kv, kv, causal=False, block_q=64, block_k=64)
    ref = reference_attention(q, kv, kv, causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    k1, k2, k3 = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(k1, (2, 64, 2, 16))
    k = jax.random.normal(k2, (2, 64, 2, 16))
    v = jax.random.normal(k3, (2, 64, 2, 16))
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pipeline_apply_matches_sequential():
    mesh = create_mesh(MeshConfig(data=2, stage=4))
    key = jax.random.key(3)
    ws = [jax.random.normal(jax.random.fold_in(key, i), (8, 8)) / 3
          for i in range(4)]
    stage_params = stack_stage_params([{"w": w} for w in ws])

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    mb = jax.random.normal(jax.random.fold_in(key, 9), (6, 4, 8))
    out = pipeline_apply(stage_fn, mesh, stage_params, mb, axis="stage")

    expect = mb
    for w in ws:
        expect = jnp.tanh(expect @ w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def _tiny_batch(cfg, batch=4):
    tokens = jax.random.randint(jax.random.key(7), (batch, 32), 0,
                                cfg.vocab_size)
    return {"tokens": tokens}


def test_gpt_forward_single_device():
    cfg = gpt.CONFIGS["nano"]
    params = gpt.init_params(cfg, jax.random.key(0))
    logits, aux = gpt.forward(params, _tiny_batch(cfg)["tokens"], cfg)
    assert logits.shape == (4, 32, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_gpt_train_step_dp_fsdp_tp():
    cfg = gpt.CONFIGS["nano"]
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    opt = optax.adam(1e-3)
    init_state, train_step = gpt.make_train_step(cfg, opt, mesh)

    state = init_state(jax.random.key(0))
    batch = shard_batch(mesh, _tiny_batch(cfg, batch=8))

    step = jax.jit(train_step, donate_argnums=0)
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]  # same batch: loss must fall
    assert np.isfinite(losses).all()


def test_gpt_moe_expert_parallel():
    cfg = gpt.CONFIGS["nano-moe"]
    mesh = create_mesh(MeshConfig(data=2, expert=4))
    opt = optax.sgd(1e-2)
    init_state, train_step = gpt.make_train_step(cfg, opt, mesh)
    state = init_state(jax.random.key(1))
    batch = shard_batch(mesh, _tiny_batch(cfg, batch=8))
    state, metrics = jax.jit(train_step)(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_gpt_seq_parallel_forward():
    cfg = gpt.CONFIGS["nano"]
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    params = gpt.init_params(cfg, jax.random.key(0))
    tokens = _tiny_batch(cfg)["tokens"]

    with_sp = jax.jit(lambda p, t: gpt.forward(p, t, cfg, mesh)[0])
    sharded = gpt.shard_params(params, mesh, cfg)
    logits_sp = with_sp(sharded, jax.device_put(
        tokens, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data", "seq"))))
    logits_ref, _ = gpt.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(logits_sp),
                               np.asarray(logits_ref), atol=2e-4, rtol=2e-4)


def test_num_params_gpt2_small():
    n = gpt.num_params(gpt.CONFIGS["gpt2-small"])
    assert 120e6 < n < 130e6


def test_gpt_train_step_seq_parallel():
    # Regression: loss_fn must keep the sequence dim divisible by the seq
    # axis (it runs the model on full L and shifts targets).
    cfg = gpt.CONFIGS["nano"]
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    opt = optax.sgd(1e-2)
    init_state, train_step = gpt.make_train_step(cfg, opt, mesh)
    state = init_state(jax.random.key(0))
    batch = shard_batch(mesh, _tiny_batch(cfg, batch=8))
    state, metrics = jax.jit(train_step)(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_single_device_mesh():
    from ray_tpu.parallel import single_device_mesh
    mesh = single_device_mesh()  # must not raise on an 8-device host
    assert all(s == 1 for s in mesh.shape.values())


def test_flash_attention_long_context_blocks():
    # Streaming-KV kernel: kv blocks much smaller than kv_len.
    k1, k2 = jax.random.split(jax.random.key(4))
    q = jax.random.normal(k1, (1, 512, 1, 64))
    kv = jax.random.normal(k2, (1, 512, 1, 64))
    out = flash_attention(q, kv, kv, causal=True, block_q=128, block_k=64)
    ref = reference_attention(q, kv, kv, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_opt_state_sharded_like_params():
    # ZeRO-3: Adam moments must inherit each param's sharding, not stay
    # replicated.
    cfg = gpt.CONFIGS["nano"]
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    init_state, _ = gpt.make_train_step(cfg, optax.adam(1e-3), mesh)
    state = init_state(jax.random.key(0))
    p_shard = state["params"]["blocks"]["w_up"].sharding
    mu = state["opt_state"][0].mu["blocks"]["w_up"]
    assert mu.sharding.is_equivalent_to(p_shard, mu.ndim)


def test_no_involuntary_rematerialization(capfd):
    """Compiled sharded train steps must not trigger XLA SPMD's
    'Involuntary full rematerialization' fallback (VERDICT r1: the r1
    rules resharded the embedding gather across transposed device orders).
    The warning is emitted on C++ stderr, so capture at the fd level."""
    import jax
    import optax

    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshConfig, create_mesh, shard_batch

    cfg = gpt.CONFIGS["nano"]
    for mesh_cfg in (MeshConfig(data=2, fsdp=2, tensor=2),
                     MeshConfig(data=2, seq=4)):
        mesh = create_mesh(mesh_cfg, devices=jax.devices()[:8])
        init_state, train_step = gpt.make_train_step(
            cfg, optax.adamw(1e-3), mesh)
        state = init_state(jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                    cfg.vocab_size)
        batch = shard_batch(mesh, {"tokens": tokens})
        state, metrics = jax.jit(train_step, donate_argnums=0)(state, batch)
        assert float(metrics["loss"]) > 0
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err


# ---------------------------------------------------------------------------
# Llama family (RMSNorm + RoPE + SwiGLU + GQA)
# ---------------------------------------------------------------------------


def _llama_batch(cfg, batch=4, seed=1):
    tokens = jax.random.randint(jax.random.key(seed), (batch, 64), 0,
                                cfg.vocab_size)
    return {"tokens": tokens}


def test_llama_memorizes_single_chip():
    from ray_tpu.models import llama
    cfg = llama.CONFIGS["llama-tiny"]
    init_state, train_step = llama.make_train_step(cfg, optax.adamw(1e-3))
    state = init_state(jax.random.key(0))
    batch = _llama_batch(cfg)
    step = jax.jit(train_step, donate_argnums=0)
    losses = []
    for _ in range(25):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 1.0, losses[::6]


def test_llama_gqa_multichip_matches_single():
    """dp x tensor x seq mesh (GQA kv heads sharded over tensor) computes
    the same loss as one device."""
    from ray_tpu.models import llama
    from ray_tpu.parallel import MeshConfig, create_mesh, shard_batch
    cfg = llama.CONFIGS["llama-tiny"]
    assert cfg.n_kv_heads < cfg.n_heads  # really grouped-query
    batch = _llama_batch(cfg, batch=8)

    params = llama.init_params(cfg, jax.random.key(0))
    single = float(llama.loss_fn(params, batch, cfg))

    mesh = create_mesh(MeshConfig(data=2, tensor=2, seq=2))
    sharded = llama.shard_params(params, mesh, cfg)
    sbatch = shard_batch(mesh, batch)
    multi = float(jax.jit(
        lambda p, b: llama.loss_fn(p, b, cfg, mesh))(sharded, sbatch))
    assert abs(single - multi) < 2e-3, (single, multi)


def test_llama_train_step_dp_fsdp_tp():
    from ray_tpu.models import llama
    from ray_tpu.parallel import MeshConfig, create_mesh, shard_batch
    cfg = llama.CONFIGS["llama-tiny"]
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    init_state, train_step = llama.make_train_step(
        cfg, optax.adam(1e-3), mesh)
    state = init_state(jax.random.key(0))
    batch = shard_batch(mesh, _llama_batch(cfg, batch=8))
    step = jax.jit(train_step, donate_argnums=0)
    losses = []
    for _ in range(3):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_llama_rope_relative_position_property():
    """RoPE scores depend only on relative position: rotating q and k by a
    shared offset leaves q.k dot products unchanged."""
    from ray_tpu.models.llama import _rope
    q = jax.random.normal(jax.random.key(0), (1, 8, 2, 16))
    k = jax.random.normal(jax.random.key(1), (1, 8, 2, 16))
    s0 = jnp.einsum("blhk,bmhk->bhlm", _rope(q, 1e4, 0), _rope(k, 1e4, 0))
    s7 = jnp.einsum("blhk,bmhk->bhlm", _rope(q, 1e4, 7), _rope(k, 1e4, 7))
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s7),
                               atol=1e-4, rtol=1e-4)


def test_llama_7b_param_count():
    from ray_tpu.models import llama
    n = llama.num_params(llama.CONFIGS["llama2-7b"])
    assert 6.5e9 < n < 7.0e9, n


@pytest.mark.slow
def test_resnet_memorizes():
    """60 adam steps of resnet18 — a learning gate, so it carries `slow`
    like the other learning gates (~90s, a tenth of the fast-suite
    budget, and it is one of the documented jax-on-CPU seed failures)."""
    from ray_tpu.models import resnet
    cfg = resnet.CONFIGS["resnet18-cifar"]
    init_state, train_step = resnet.make_train_step(cfg, optax.adam(3e-3))
    state = init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {"images": jnp.asarray(rng.normal(size=(16, 32, 32, 3)),
                                   jnp.float32),
             "labels": jnp.asarray(rng.integers(0, 10, 16))}
    step = jax.jit(train_step, donate_argnums=0)
    for _ in range(60):
        state, m = step(state, batch)
    assert float(m["loss"]) < 1.5
    assert float(m["accuracy"]) > 0.5


def test_flash_attention_non_power_of_two_multiple_stays_pallas():
    """L=1536 tiles at 512 even though the default block is 1024 — the
    halving fit must keep such lengths on the Pallas path (regression:
    raising default blocks must not fall back to [L,L] XLA attention)."""
    from ray_tpu.ops.attention import _fit_blocks
    assert _fit_blocks(1536, 1536, 1024, 1024) == (512, 512)
    assert _fit_blocks(1024, 1024, 1024, 1024) == (1024, 1024)
    assert _fit_blocks(96, 96, 1024, 1024)[0] <= 96  # shorter than a block
    q = jax.random.normal(jax.random.key(0), (1, 1536, 2, 32))
    k = jax.random.normal(jax.random.key(1), (1, 1536, 2, 32))
    v = jax.random.normal(jax.random.key(2), (1, 1536, 2, 32))
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_spmd_fused_ce_matches_naive_dp2_tp2():
    """Numerics gate for the mesh fused cross-entropy: loss AND grads at
    dp2/tp2(/sp2) must match the naive materialized-logits loss to fp32
    epsilon (VERDICT r2 item: the mesh path must never re-pay the [T,V]
    materialization the single-chip bench eliminated)."""
    from ray_tpu.ops.cross_entropy import (fused_cross_entropy_spmd,
                                           spmd_ce_applicable)

    B, L, D, V = 4, 8, 16, 32
    x = jax.random.normal(jax.random.key(0), (B, L, D), jnp.float32)
    head = jax.random.normal(jax.random.key(1), (D, V), jnp.float32)
    t = jax.random.randint(jax.random.key(2), (B, L), 0, V)
    valid = jnp.ones((B, L), jnp.float32).at[:, -1].set(0.0)

    def naive(x, head):
        logits = (x @ head).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        nll = -jnp.take_along_axis(logp, t[..., None], -1)[..., 0]
        return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)

    ln = naive(x, head)
    gn = jax.grad(naive, argnums=(0, 1))(x, head)
    for shape in (MeshConfig(data=2, fsdp=1, tensor=2, seq=2),
                  MeshConfig(data=2, fsdp=2, tensor=2, seq=1)):
        mesh = create_mesh(shape)
        assert spmd_ce_applicable(mesh, V, B, L)
        with mesh:
            def f(x, h):
                return fused_cross_entropy_spmd(x, h, t, valid, mesh)
            ls = jax.jit(f)(x, head)
            gs = jax.jit(jax.grad(f, argnums=(0, 1)))(x, head)
        assert abs(float(ln - ls)) < 1e-5
        assert float(jnp.max(jnp.abs(gn[0] - gs[0]))) < 1e-6
        assert float(jnp.max(jnp.abs(gn[1] - gs[1]))) < 1e-6


def test_gpt_mesh_loss_uses_spmd_fused_ce(monkeypatch):
    """The model loss under a mesh must route through the shard_map fused
    CE (not the materialized-logits fallback) for divisible shapes."""
    from ray_tpu.ops import cross_entropy as ce

    called = {}
    real = ce.fused_cross_entropy_spmd

    def spy(x, head, targets, valid, mesh, n_chunks=4):
        called["hit"] = True
        return real(x, head, targets, valid, mesh, n_chunks)

    monkeypatch.setattr(ce, "fused_cross_entropy_spmd", spy)
    cfg = gpt.CONFIGS["nano"]
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    params = gpt.shard_params(gpt.init_params(cfg, jax.random.key(0)),
                              mesh, cfg)
    batch = shard_batch(mesh, _tiny_batch(cfg, batch=8))
    with mesh:
        loss = jax.jit(lambda p, b: gpt.loss_fn(p, b, cfg, mesh))(
            params, batch)
    assert np.isfinite(float(loss))
    assert called.get("hit")


# ---------------------------------------------------------------------------
# Multi-slice (two-level dcn x ici) meshes — SURVEY §2.5 DCN mapping, §7 P7.
# ---------------------------------------------------------------------------


def test_two_level_mesh_topology():
    """Walking an ICI-only axis must stay inside one slice; the data
    axis is the only one allowed to cross the DCN boundary."""
    from ray_tpu.parallel import (
        MeshConfig, create_two_level_mesh, slice_index_of)
    mesh = create_two_level_mesh(
        ici=MeshConfig(data=1, fsdp=2, tensor=2), dcn=MeshConfig(data=2),
        n_slices=2, devices=jax.devices()[:8])
    assert mesh.shape["data"] == 2
    assert mesh.shape["fsdp"] == 2 and mesh.shape["tensor"] == 2
    slc = slice_index_of(mesh, 2)
    names = list(mesh.axis_names)
    for ax in ("fsdp", "tensor"):
        assert (np.diff(slc, axis=names.index(ax)) == 0).all(), \
            f"ICI axis {ax} crosses a slice boundary"
    # data axis DOES cross: both slices appear along it.
    d = names.index("data")
    moved = np.moveaxis(slc, d, 0).reshape(2, -1)
    assert (moved[0] != moved[1]).all()


def test_two_level_mesh_data_split_across_both():
    """data = dcn_part x ici_part: high-order digits cross slices,
    low-order stay inside."""
    from ray_tpu.parallel import (
        MeshConfig, create_two_level_mesh, slice_index_of)
    mesh = create_two_level_mesh(
        ici=MeshConfig(data=2, tensor=2), dcn=MeshConfig(data=2),
        n_slices=2, devices=jax.devices()[:8])
    assert mesh.shape["data"] == 4
    slc = slice_index_of(mesh, 2)
    names = list(mesh.axis_names)
    along = np.moveaxis(slc, names.index("data"), 0).reshape(4, -1)
    # positions 0,1 = slice A's ici block; 2,3 = slice B's.
    assert (along[0] == along[1]).all()
    assert (along[2] == along[3]).all()
    assert (along[0] != along[2]).all()


def test_two_level_mesh_rejects_tensor_over_dcn():
    from ray_tpu.parallel import MeshConfig, create_two_level_mesh
    with pytest.raises(ValueError, match="inside a slice"):
        create_two_level_mesh(
            ici=MeshConfig(data=4), dcn=MeshConfig(data=1, tensor=2),
            n_slices=2, devices=jax.devices()[:8])


def test_two_level_mesh_numerics_match_flat():
    """Same logical dp2/fsdp2/tp2 sharding on a two-level mesh must
    produce the same loss as the flat mesh (only the device->position
    assignment differs)."""
    from ray_tpu.parallel import MeshConfig, create_two_level_mesh
    cfg = gpt.CONFIGS["nano"]
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                cfg.vocab_size)

    def loss_on(mesh):
        init, step = gpt.make_train_step(cfg, optax.adamw(1e-3), mesh)
        state = init(jax.random.key(0))
        _state, metrics = jax.jit(step, donate_argnums=0)(
            state, shard_batch(mesh, {"tokens": tokens}))
        return float(metrics["loss"])

    flat = loss_on(create_mesh(MeshConfig(data=2, fsdp=2, tensor=2),
                               devices=jax.devices()[:8]))
    two = loss_on(create_two_level_mesh(
        ici=MeshConfig(data=1, fsdp=2, tensor=2), dcn=MeshConfig(data=2),
        n_slices=2, devices=jax.devices()[:8]))
    assert abs(flat - two) < 1e-4


def test_stage_slice_plan_contiguous_blocks():
    """Gangs pack into contiguous per-slice blocks, so pipeline cuts
    fall on DCN boundaries only where the slice count forces them."""
    from ray_tpu.parallel import (
        dcn_cut_edges, pipeline_placement_resources, stage_slice_plan)

    plan = stage_slice_plan(4, 2)
    assert plan == [0, 0, 1, 1]
    # v=1 (4 chunks on 4 gangs): exactly one DCN cut, at the block edge.
    assert dcn_cut_edges(plan, 4) == [(1, 2)]
    # v=2 (8 chunks looping over the same 4 gangs): the looping schedule
    # wraps gang 3 -> gang 0 once, adding the wraparound cut.
    assert dcn_cut_edges(plan, 8) == [(1, 2), (3, 4), (5, 6)]
    res = pipeline_placement_resources(plan)
    assert res == [{"pp_slice_0": 1}, {"pp_slice_0": 1},
                   {"pp_slice_1": 1}, {"pp_slice_1": 1}]
    # Degenerate single-slice plan: no cuts anywhere.
    assert dcn_cut_edges(stage_slice_plan(4, 1), 8) == []
    with pytest.raises(ValueError, match="not divisible"):
        stage_slice_plan(4, 3)


def test_chunk_assignment_round_robin():
    """Interleaved chunk ownership is round-robin (non-adjacent), and
    adjacent chunks always land on adjacent gangs — the property
    stage_slice_plan's contiguous blocks rely on for ICI locality."""
    from ray_tpu.parallel import chunk_assignment

    assert chunk_assignment(4, 4) == [[0], [1], [2], [3]]
    assert chunk_assignment(4, 2) == [[0, 2], [1, 3]]
    assert chunk_assignment(8, 2) == [[0, 2, 4, 6], [1, 3, 5, 7]]
    owner = {c: g for g, cs in enumerate(chunk_assignment(8, 4))
             for c in cs}
    for c in range(7):
        assert (owner[c + 1] - owner[c]) % 4 == 1
    with pytest.raises(ValueError, match="not divisible"):
        chunk_assignment(6, 4)
