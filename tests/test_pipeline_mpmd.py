"""MPMD pipeline-parallel trainer (PR 15): parity with the single-program
dryrun, schedule equivalence, and the robustness headline — a stage gang
dying mid-run re-forms in place and converges loss-exact.

The numpy MLP quartet below runs stage workers jax-free (workers never
pay the jax import), so the chaos scenarios stay fast; the parity gate
uses `jax_stage_fns` against the real `parallel/pipeline.py` dryrun.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.util import metrics as mt

D = 8
N_MICRO = 6
N_STAGES = 4


# ---------------------------------------------------------------------------
# numpy stage quartet (stage workers never import jax)
# ---------------------------------------------------------------------------

def np_stage_fwd(params, x):
    y = np.tanh(x @ params["w"] + params["b"])
    return y, (x, y)


def np_stage_bwd(params, cache, gy):
    x, y = cache
    gz = gy * (1.0 - y * y)
    return gz @ params["w"].T, {"w": x.T @ gz, "b": gz.sum(axis=0)}


def np_loss_fwd(y, t):
    d = y - t
    return float((d * d).mean()), (d, y.size)


def np_loss_bwd(cache):
    d, n = cache
    return 2.0 * d / n


def slow_stage_fwd(params, x):
    # Paces pipeline steps so a scripted hostd-kill heartbeat tick lands
    # mid-run instead of racing trainer setup.
    time.sleep(0.1)
    return np_stage_fwd(params, x)


NP_FNS = (np_stage_fwd, np_stage_bwd, np_loss_fwd, np_loss_bwd)
SLOW_FNS = (slow_stage_fwd, np_stage_bwd, np_loss_fwd, np_loss_bwd)


def mk_params(n_stages=N_STAGES, width=D, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": rng.normal(0, 0.3, (width, width)), "b": np.zeros(width)}
            for _ in range(n_stages)]


def mk_data(step, n_micro=N_MICRO, micro_b=4, width=D):
    r = np.random.default_rng(1000 + step)
    xs = [r.normal(size=(micro_b, width)) for _ in range(n_micro)]
    ts = [np.tanh(x @ np.ones((width, width)) * 0.1) for x in xs]
    return xs, ts


def _recoveries(kind):
    return float(mt.read("pp_recoveries", {"kind": kind}) or 0.0)


# ---------------------------------------------------------------------------
# parity + schedules (plain cluster)
# ---------------------------------------------------------------------------

@pytest.fixture
def pp_cluster():
    info = ray_tpu.init(num_cpus=8, object_store_memory=256 << 20)
    try:
        yield info
    finally:
        ray_tpu.shutdown()


def test_mpmd_parity_with_single_program_dryrun(pp_cluster):
    """The standing parity gate: the MPMD trainer and the GPipe ppermute
    dryrun run the same microbatch schedule over the same params and
    must agree on loss to fp tolerance."""
    import jax.numpy as jnp

    from ray_tpu.parallel import (MeshConfig, create_mesh,
                                  pipeline_loss_dryrun, stack_stage_params)
    from ray_tpu.train import PipelineTrainer, jax_stage_fns

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    params = mk_params()
    xs, ts = mk_data(0)

    mesh = create_mesh(MeshConfig(data=2, stage=N_STAGES))
    stacked = stack_stage_params(
        [{"w": jnp.asarray(p["w"]), "b": jnp.asarray(p["b"])}
         for p in params])
    dry = float(pipeline_loss_dryrun(
        stage_fn, loss_fn, mesh, stacked,
        jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ts))))

    tr = PipelineTrainer(jax_stage_fns(stage_fn, loss_fn), params,
                         n_microbatches=N_MICRO)
    try:
        mpmd = tr.forward_only(xs, ts)
    finally:
        tr.shutdown()
    assert mpmd == pytest.approx(dry, rel=1e-5), \
        f"MPMD loss {mpmd} != dryrun loss {dry}"


def test_1f1b_and_gpipe_schedules_loss_identical(pp_cluster):
    """Both schedules execute the same microbatch set with per-mb grads
    folded in sorted order, so the SGD trajectory is bit-identical;
    queue_depth=1 (tightest backpressure) must not change the math."""
    from ray_tpu.train import PipelineTrainer

    losses = {}
    for key, schedule, qd in (("1f1b", "1f1b", 2), ("gpipe", "gpipe", 2),
                              ("1f1b_q1", "1f1b", 1)):
        tr = PipelineTrainer(NP_FNS, mk_params(), lr=0.1,
                             n_microbatches=N_MICRO, schedule=schedule,
                             queue_depth=qd)
        try:
            losses[key] = [h["loss"] for h in tr.fit(mk_data, 3)]
        finally:
            tr.shutdown()
    assert losses["1f1b"] == losses["gpipe"]
    assert losses["1f1b"] == losses["1f1b_q1"]
    # Loss actually decreases (the pipeline is really training).
    assert losses["1f1b"][-1] < losses["1f1b"][0]


def test_worker_group_pg_cleanup_on_wait_failure(pp_cluster):
    """WorkerGroup partial-failure hygiene: if pg.wait() itself raises
    (not just times out), the just-created placement group must be
    removed before the error propagates — repeated elastic restarts
    must not leak reservations."""
    import importlib

    from ray_tpu.train import WorkerGroup

    # `ray_tpu.util.placement_group` the module is shadowed by the
    # same-named factory function on the package, so go via importlib.
    pg_mod = importlib.import_module("ray_tpu.util.placement_group")

    base = ray_tpu.available_resources().get("CPU", 0.0)
    assert base >= 4

    orig = pg_mod.PlacementGroup.wait

    def boom(self, timeout=None):
        raise ConnectionError("injected GCS hiccup during pg.wait")

    pg_mod.PlacementGroup.wait = boom
    try:
        with pytest.raises(ConnectionError):
            WorkerGroup(num_workers=4, resources_per_worker={"CPU": 1})
    finally:
        pg_mod.PlacementGroup.wait = orig
    deadline = time.monotonic() + 10
    avail = -1.0
    while time.monotonic() < deadline:
        avail = ray_tpu.available_resources().get("CPU", 0.0)
        if avail == base:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"PG reservation leaked: {avail} CPUs available, expected {base}")


def test_stage_group_pg_cleanup_on_setup_failure(pp_cluster):
    """StageGroup applies the same hygiene: a spec that makes setup()
    blow up must not leave the stage's PG bundles reserved."""
    from ray_tpu.train.pipeline_stage import StageGroup

    base = ray_tpu.available_resources().get("CPU", 0.0)
    spec = {"stage": 0, "n_stages": 1, "stage_fwd": np_stage_fwd,
            "stage_bwd": np_stage_bwd, "loss_fwd": np_loss_fwd,
            "loss_bwd": np_loss_bwd, "params": mk_params(1)[0],
            "lr": "not-a-float"}
    with pytest.raises(Exception):
        StageGroup(0, spec, 2, {"CPU": 1})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU", 0.0) == base:
            return
        time.sleep(0.1)
    raise AssertionError("StageGroup leaked its placement group")


# ---------------------------------------------------------------------------
# chaos gates
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_stage_kill_surgical_replay_loss_exact(tmp_path):
    """The robustness headline: a scripted chaos kill takes down one
    stage's actor mid-schedule; only that stage re-forms (surgical
    replay of the in-flight step's microbatches from upstream sealed
    outputs), the other stages never restart or recompute, and the
    final losses exactly match an uninterrupted run."""
    from ray_tpu.train import PipelineTrainer

    ray_tpu.init(num_cpus=8, object_store_memory=256 << 20,
                 _system_config={
                     "chaos_enabled": True,
                     "chaos_seed": 7,
                     # The four stage actors are this cluster's first
                     # worker spawns (salts "1".."4"); "2" is mapped to
                     # its stage below via ident().  Per-worker task
                     # index 25 lands mid-step-1: 3 boot tasks
                     # (create/setup/ident) + 15 step-0 tasks
                     # (6 fwd + 6 bwd + partial + apply + save).
                     "chaos_kill_worker_salts": "2",
                     "chaos_kill_worker_at": 25,
                     "chaos_max_faults": 1,
                 })
    try:
        replays0 = _recoveries("replay")
        tr = PipelineTrainer(NP_FNS, mk_params(), lr=0.1,
                             n_microbatches=N_MICRO,
                             storage_path=str(tmp_path / "chaos"),
                             ckpt_every=1, stage_timeout_s=15.0)
        before = tr.stage_idents()
        victim = next(i for i, idents in enumerate(before)
                      if idents[0]["salt"] == "2")
        chaos_losses = [h["loss"] for h in tr.fit(mk_data, 4)]
        after = tr.stage_idents()
        assert tr._recoveries == 1
        assert _recoveries("replay") == replays0 + 1
        assert _recoveries("rollback") == 0
        # Only the killed stage re-formed; survivors kept their pids.
        assert after[victim][0]["pid"] != before[victim][0]["pid"]
        for i in range(N_STAGES):
            if i != victim:
                assert after[i][0]["pid"] == before[i][0]["pid"], \
                    f"stage {i} restarted but was never killed"
        # Only the in-flight step's microbatches replayed: survivors ran
        # exactly the clean-run op count (fwd+bwd per microbatch plus
        # partial+apply per step — no recomputation).
        stats = {s["stage"]: s
                 for s in ray_tpu.get([g.members[0].stats.remote()
                                       for g in tr.groups], timeout=30)}
        clean_ops = 4 * (2 * N_MICRO + 2)
        for i in range(N_STAGES):
            if i != victim:
                assert stats[i]["ops"] == clean_ops, \
                    f"stage {i} ops {stats[i]['ops']} != {clean_ops}"
        tr.shutdown()

        # Uninterrupted reference run in the same cluster (fresh worker
        # spawn ordinals, so the scripted kill cannot re-fire).
        tr2 = PipelineTrainer(NP_FNS, mk_params(), lr=0.1,
                              n_microbatches=N_MICRO,
                              storage_path=str(tmp_path / "clean"),
                              ckpt_every=1)
        clean_losses = [h["loss"] for h in tr2.fit(mk_data, 4)]
        assert tr2._recoveries == 0
        tr2.shutdown()
        assert chaos_losses == clean_losses, \
            f"loss diverged: {chaos_losses} vs {clean_losses}"
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


# `slow`: ~28s = 3% of the tier-1 budget, and the interleaved+pre-push
# hostd-kill gate below exercises a strict superset of this rollback
# path; the stage-kill surgical-replay gate stays in tier-1.
@pytest.mark.slow
@pytest.mark.chaos
def test_hostd_kill_pipeline_resumes_from_committed(tmp_path):
    """Deterministic pipeline-under-node-loss gate: a scripted
    `chaos_kill_hostd_salts` kill takes down the node hosting the stage
    gangs — workers AND that node's object store — at an exact
    heartbeat ordinal.  The gangs must re-form on the spare node,
    recover from the latest COMMITTED per-stage checkpoints, and the
    final losses must exactly match a clean run.

    Placement is made deterministic by construction order: at trainer
    build time node2 is the only node with CPUs, so both stages land
    there; the spare node joins before the kill tick fires."""
    from ray_tpu._private import node as node_mod
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import PipelineTrainer

    params = mk_params(2)

    # Hostd spawn ordinals are a process-global sequence; compute the
    # victim's salt relative to wherever the counter currently is
    # (head = base+1, node2 = base+2, spare = base+3).
    base = node_mod._hostd_spawn_seq
    os.environ["RAY_TPU_CHAOS_ENABLED"] = "1"
    os.environ["RAY_TPU_CHAOS_KILL_HOSTD_SALTS"] = f"h{base + 2}"
    # Tick 10 at the 0.5s heartbeat = ~5s after node2 boots: after
    # trainer setup (~2s), mid-fit (the slow_stage_fwd pacing keeps the
    # 10-step run alive well past the tick).
    os.environ["RAY_TPU_CHAOS_KILL_HOSTD_AT"] = "10"
    GLOBAL_CONFIG.invalidate_cache()
    cluster = None
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 0})
        cluster.add_node(num_cpus=2)            # node2: the victim
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.gcs_address)

        tr = PipelineTrainer(SLOW_FNS, params, lr=0.1,
                             n_microbatches=N_MICRO,
                             storage_path=str(tmp_path / "nodeloss"),
                             ckpt_every=1, stage_timeout_s=20.0,
                             max_failures=4)
        before = tr.stage_idents()
        cluster.add_node(num_cpus=2)            # the failover target
        cluster.wait_for_nodes()

        chaos_losses = [h["loss"] for h in tr.fit(mk_data, 10)]
        after = tr.stage_idents()
        assert tr._recoveries >= 1, "hostd kill never disturbed the run"
        # Every gang moved off the dead node.
        dead = {idents[0]["node_id"] for idents in before}
        assert len(dead) == 1                   # both stages were packed
        for idents in after:
            assert idents[0]["node_id"] not in dead
        tr.shutdown()
        ray_tpu.shutdown()
    finally:
        for k in ("RAY_TPU_CHAOS_ENABLED", "RAY_TPU_CHAOS_KILL_HOSTD_SALTS",
                  "RAY_TPU_CHAOS_KILL_HOSTD_AT"):
            os.environ.pop(k, None)
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                pass

    # Clean reference run (fresh single-node cluster, chaos off).
    ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    try:
        tr2 = PipelineTrainer(SLOW_FNS, mk_params(2), lr=0.1,
                              n_microbatches=N_MICRO,
                              storage_path=str(tmp_path / "clean2"),
                              ckpt_every=1)
        clean_losses = [h["loss"] for h in tr2.fit(mk_data, 10)]
        assert tr2._recoveries == 0
        tr2.shutdown()
    finally:
        ray_tpu.shutdown()
    assert chaos_losses == clean_losses, \
        f"loss diverged after node loss: {chaos_losses} vs {clean_losses}"


# ---------------------------------------------------------------------------
# interleaved schedule + pre-pushed activations (PR 18)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_interleaved_prepush_bit_exact(pp_cluster):
    """The interleaved (looping) schedule and the pre-push receive
    window change only WHEN work runs and HOW bytes move — per-chunk
    grads still fold in sorted microbatch order, so every (schedule,
    interleave, prefetch, backpressure) combination must produce the
    bit-identical SGD trajectory."""
    from ray_tpu.parallel import chunk_assignment
    from ray_tpu.train import PipelineTrainer

    losses = {}
    stats = {}
    for key, kw in (
            ("base", dict(schedule="1f1b")),
            ("v2_1f1b", dict(schedule="1f1b", interleave=2,
                             prefetch=True)),
            ("v2_gpipe", dict(schedule="gpipe", interleave=2,
                              prefetch=True)),
            ("v1_prepush", dict(schedule="1f1b", prefetch=True)),
            ("v2_tight", dict(schedule="1f1b", interleave=2,
                              prefetch=True, queue_depth=1,
                              recv_window=1)),
    ):
        tr = PipelineTrainer(NP_FNS, mk_params(), lr=0.1,
                             n_microbatches=N_MICRO, **kw)
        try:
            if kw.get("interleave"):
                assert tr._assignment == chunk_assignment(
                    N_STAGES, N_STAGES // kw["interleave"])
            losses[key] = [h["loss"] for h in tr.fit(mk_data, 3)]
            stats[key] = [m for gang in tr.stage_stats() for m in gang]
        finally:
            tr.shutdown()
    for key in losses:
        assert losses[key] == losses["base"], \
            f"{key} diverged: {losses[key]} vs {losses['base']}"
    assert losses["base"][-1] < losses["base"][0]
    # The overlap actually happened (prefetched activations were
    # consumed from the window), and the backpressure bound held: at
    # most recv_window resident per chunk, +1 while a consuming forward
    # is mid-execution.
    for key, window in (("v2_1f1b", 2), ("v1_prepush", 2),
                        ("v2_tight", 1)):
        hits = sum(m["recv_hits"] for m in stats[key])
        peak = max(m["recv_peak"] for m in stats[key])
        assert hits > 0, f"{key}: prefetch window never hit"
        assert peak <= window + 1, \
            f"{key}: recv_peak {peak} breached window {window}"
    # No prefetch => the window is never touched.
    assert all(m["recv_hits"] == 0 and m["recv_peak"] == 0
               for m in stats["base"])


@pytest.mark.slow
def test_interleaved_parity_with_dryrun(pp_cluster):
    """The standing dryrun parity gate rerun under interleave=2 +
    pre-push: chunked gangs and overlapped transfer must not move the
    forward loss by more than fp tolerance vs the single-program GPipe
    schedule."""
    import jax.numpy as jnp

    from ray_tpu.parallel import (MeshConfig, create_mesh,
                                  pipeline_loss_dryrun, stack_stage_params)
    from ray_tpu.train import PipelineTrainer, jax_stage_fns

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    params = mk_params()
    xs, ts = mk_data(0)
    mesh = create_mesh(MeshConfig(data=2, stage=N_STAGES))
    stacked = stack_stage_params(
        [{"w": jnp.asarray(p["w"]), "b": jnp.asarray(p["b"])}
         for p in params])
    dry = float(pipeline_loss_dryrun(
        stage_fn, loss_fn, mesh, stacked,
        jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ts))))

    tr = PipelineTrainer(jax_stage_fns(stage_fn, loss_fn), params,
                         n_microbatches=N_MICRO, interleave=2,
                         prefetch=True)
    try:
        mpmd = tr.forward_only(xs, ts)
    finally:
        tr.shutdown()
    assert mpmd == pytest.approx(dry, rel=1e-5), \
        f"interleaved MPMD loss {mpmd} != dryrun loss {dry}"


@pytest.mark.slow
def test_topology_placement_pins_gangs_to_slices():
    """Topology-aware placement: a stage_slice_plan turned into
    placement resources must pin each gang to the node advertising its
    slice, so chunk hand-offs cross the (simulated) DCN boundary only
    where dcn_cut_edges says they do."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.parallel import (dcn_cut_edges,
                                  pipeline_placement_resources,
                                  stage_slice_plan)
    from ray_tpu.train import PipelineTrainer

    cluster = None
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 0})
        cluster.add_node(num_cpus=2, resources={"pp_slice_0": 4})
        cluster.add_node(num_cpus=2, resources={"pp_slice_1": 4})
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.gcs_address)

        plan = stage_slice_plan(2, 2)               # one gang per slice
        tr = PipelineTrainer(
            NP_FNS, mk_params(), lr=0.1, n_microbatches=N_MICRO,
            interleave=2, prefetch=True,
            placement_plan=pipeline_placement_resources(plan))
        try:
            losses = [h["loss"] for h in tr.fit(mk_data, 2)]
            assert losses[-1] < losses[0]
            # Map node -> advertised slice resource, then check every
            # gang member landed inside its assigned slice.
            slice_of_node = {}
            for n in ray_tpu.nodes():
                for s in (0, 1):
                    if n["Resources"].get(f"pp_slice_{s}"):
                        slice_of_node[n["NodeID"]] = s
            for g, idents in enumerate(tr.stage_idents()):
                for ident in idents:
                    assert slice_of_node.get(ident["node_id"]) == \
                        plan[g], (f"gang {g} member on node "
                                  f"{ident['node_id']} outside slice "
                                  f"{plan[g]}")
            # The placement plan cut the 4-chunk loop at every gang
            # hand-off (2 gangs in 2 slices, interleaved): the oracle
            # agrees.
            assert dcn_cut_edges(plan, N_STAGES) == [(0, 1), (1, 2),
                                                     (2, 3)]
        finally:
            tr.shutdown()
        ray_tpu.shutdown()
    finally:
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                pass


@pytest.mark.slow
def test_stage_kill_surgical_replay_interleaved_prepush(tmp_path):
    """The PR-15 surgical-replay gate rerun under interleave=2 +
    pre-push: a chaos kill takes down one gang (two non-adjacent
    chunks) mid-schedule; only that gang re-forms and replays, the
    survivor keeps its pid and exact clean op count, prefetched-but-
    unconsumed activations are re-pushed, and losses exactly match an
    uninterrupted interleaved run."""
    from ray_tpu.train import PipelineTrainer

    ray_tpu.init(num_cpus=8, object_store_memory=256 << 20,
                 _system_config={
                     "chaos_enabled": True,
                     "chaos_seed": 7,
                     # Two gangs (salts "1", "2"), one member each.  Per
                     # clean step a gang worker runs 12 fwd + 12 bwd +
                     # partial + apply + save = 27 compute tasks plus 12
                     # received prefetch tasks = 39; boot is 3 tasks
                     # (create/setup/ident).  Ordinal 60 therefore lands
                     # mid-step-1 compute (step 1 spans ordinals
                     # 43..81), regardless of how prefetch resolves
                     # interleave with compute on the victim.
                     "chaos_kill_worker_salts": "2",
                     "chaos_kill_worker_at": 60,
                     "chaos_max_faults": 1,
                 })
    try:
        replays0 = _recoveries("replay")
        kw = dict(lr=0.1, n_microbatches=N_MICRO, interleave=2,
                  prefetch=True, ckpt_every=1)
        tr = PipelineTrainer(NP_FNS, mk_params(), stage_timeout_s=15.0,
                             storage_path=str(tmp_path / "chaos"), **kw)
        before = tr.stage_idents()
        victim = next(g for g, idents in enumerate(before)
                      if idents[0]["salt"] == "2")
        chaos_losses = [h["loss"] for h in tr.fit(mk_data, 4)]
        after = tr.stage_idents()
        assert tr._recoveries == 1
        assert _recoveries("replay") == replays0 + 1
        # Only the killed gang re-formed; the survivor kept its pid and
        # ran exactly the clean op count (no recomputation): per step
        # 2 chunks x (6 fwd + 6 bwd) + partial + apply = 26 ops.
        survivor = 1 - victim
        assert after[victim][0]["pid"] != before[victim][0]["pid"]
        assert after[survivor][0]["pid"] == before[survivor][0]["pid"]
        stats = tr.stage_stats()
        assert stats[survivor][0]["ops"] == 4 * (2 * 2 * N_MICRO + 2)
        tr.shutdown()

        # Uninterrupted interleaved reference run in the same cluster
        # (fresh worker spawn ordinals, so the kill cannot re-fire).
        tr2 = PipelineTrainer(NP_FNS, mk_params(),
                              storage_path=str(tmp_path / "clean"), **kw)
        clean_losses = [h["loss"] for h in tr2.fit(mk_data, 4)]
        assert tr2._recoveries == 0
        tr2.shutdown()
        assert chaos_losses == clean_losses, \
            f"loss diverged: {chaos_losses} vs {clean_losses}"
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


@pytest.mark.slow
@pytest.mark.chaos
def test_hostd_kill_interleaved_prepush_rolls_back(tmp_path):
    """The PR-15 node-loss gate rerun under interleave=2 + pre-push: a
    scripted hostd kill takes down the node hosting both gangs AND its
    object store (sealed activations + parked receive windows die with
    it), forcing the rollback path; the gangs re-form on the spare node
    and the final losses exactly match a clean interleaved run."""
    from ray_tpu._private import node as node_mod
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import PipelineTrainer

    base = node_mod._hostd_spawn_seq
    os.environ["RAY_TPU_CHAOS_ENABLED"] = "1"
    os.environ["RAY_TPU_CHAOS_KILL_HOSTD_SALTS"] = f"h{base + 2}"
    os.environ["RAY_TPU_CHAOS_KILL_HOSTD_AT"] = "10"
    GLOBAL_CONFIG.invalidate_cache()
    kw = dict(lr=0.1, n_microbatches=N_MICRO, interleave=2,
              prefetch=True, ckpt_every=1)
    cluster = None
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 0})
        cluster.add_node(num_cpus=2)            # node2: the victim
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.gcs_address)

        tr = PipelineTrainer(SLOW_FNS, mk_params(),
                             storage_path=str(tmp_path / "nodeloss"),
                             stage_timeout_s=20.0, max_failures=4, **kw)
        before = tr.stage_idents()
        cluster.add_node(num_cpus=2)            # the failover target
        cluster.wait_for_nodes()

        chaos_losses = [h["loss"] for h in tr.fit(mk_data, 6)]
        after = tr.stage_idents()
        assert tr._recoveries >= 1, "hostd kill never disturbed the run"
        dead = {idents[0]["node_id"] for idents in before}
        assert len(dead) == 1                   # both gangs were packed
        for idents in after:
            assert idents[0]["node_id"] not in dead
        tr.shutdown()
        ray_tpu.shutdown()
    finally:
        for k in ("RAY_TPU_CHAOS_ENABLED", "RAY_TPU_CHAOS_KILL_HOSTD_SALTS",
                  "RAY_TPU_CHAOS_KILL_HOSTD_AT"):
            os.environ.pop(k, None)
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                pass

    # Clean interleaved reference run (fresh cluster, chaos off).
    ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    try:
        tr2 = PipelineTrainer(SLOW_FNS, mk_params(),
                              storage_path=str(tmp_path / "clean2"), **kw)
        clean_losses = [h["loss"] for h in tr2.fit(mk_data, 6)]
        assert tr2._recoveries == 0
        tr2.shutdown()
    finally:
        ray_tpu.shutdown()
    assert chaos_losses == clean_losses, \
        f"loss diverged: {chaos_losses} vs {clean_losses}"
