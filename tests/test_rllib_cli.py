"""rllib CLI entry points (reference: rllib/train.py, rllib/evaluate.py)."""
import io
from contextlib import redirect_stdout

import pytest


@pytest.mark.slow
def test_rllib_cli_train_and_evaluate(tmp_path):
    from ray_tpu.scripts import cli

    out_dir = str(tmp_path / "ckpt")
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["rllib", "train", "--algo", "PPO",
                       "--env", "CartPole-v1", "--num-workers", "1",
                       "--stop-iters", "3", "--config",
                       '{"train_batch_size": 512, "num_sgd_iter": 2}',
                       "--out", out_dir])
    assert rc == 0
    assert "iter" in buf.getvalue() and "checkpoint written" in buf.getvalue()

    buf2 = io.StringIO()
    with redirect_stdout(buf2):
        rc = cli.main(["rllib", "evaluate", out_dir, "--algo", "PPO",
                       "--env", "CartPole-v1", "--episodes", "3"])
    assert rc == 0
    assert "episodes: mean=" in buf2.getvalue()
