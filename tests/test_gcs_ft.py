"""GCS crash/restart + network-partition chaos gates (reference:
python/ray/tests/test_gcs_fault_tolerance.py — head death, restart,
and the raylet-side resubscribe/reconnect paths; here driven by the
deterministic chaos plane instead of external process managers).

Covers the "survive the head" acceptance gates:

1. serve traffic rides through a SCRIPTED GCS kill + supervised restart
   with zero failed requests (the data plane never routes through the
   head; control-plane calls buffer-and-retry across the outage)
2. a training run rides through the same kill loss-exact — no recovery
   burned, final weights bit-identical to the unfaulted closed form
3. a partition-then-heal cycle fences the stale node: the healed hostd
   discovers its own death on re-register, kills its stale workers, and
   rejoins as the next node incarnation (split-brain containment)

plus unit tests for the sustained per-link blackhole plane and the
GcsClient outage ride-through.
"""

import asyncio
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.rpc import EventLoopThread, GcsClient, RpcServer

pytestmark = pytest.mark.chaos


def _metric(name, labels=None):
    from ray_tpu.util import metrics
    return metrics.read(name, labels) or 0.0


# ---------------------------------------------------------------------------
# Unit: sustained per-link blackholes (chaos_partition_links)
# ---------------------------------------------------------------------------

@pytest.fixture
def _link_env():
    """Config + gcs-address label sandbox for link_fault unit tests."""
    saved_gcs = fi._gcs_address
    try:
        yield
    finally:
        fi._gcs_address = saved_gcs
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG.invalidate_cache()


def test_link_fault_window_opens_at_ordinal_and_heals(_link_env):
    """A rule 'src>dst@at+dur' blackholes that link starting at exactly
    the src process's `at`-th call on the link, for `dur` wall-clock
    seconds, then heals — and never re-fires."""
    GLOBAL_CONFIG.apply_system_config(
        {"chaos_partition_links": "h2>10.0.0.1:5@2+0.15"})
    c = fi.ChaosController(1, salt="h2")
    # Ordinals 0 and 1 pass; ordinal 2 opens the window.
    assert c.link_fault("10.0.0.1:5") is False
    assert c.link_fault("10.0.0.1:5") is False
    assert c.link_fault("10.0.0.1:5") is True
    assert c.link_fault("10.0.0.1:5") is True   # still inside the window
    time.sleep(0.2)
    assert c.link_fault("10.0.0.1:5") is False  # healed
    assert c.link_fault("10.0.0.1:5") is False  # and stays healed
    assert c.faults_injected == 1  # the whole window costs one fault


def test_link_fault_is_directional(_link_env):
    """'h2>addr' cuts only h2's OUTBOUND sends: the reverse direction
    (any other process to the same address) is untouched — asymmetric
    partitions are expressible."""
    GLOBAL_CONFIG.apply_system_config(
        {"chaos_partition_links": "h2>10.0.0.1:5@0+30.0"})
    victim = fi.ChaosController(1, salt="h2")
    driver = fi.ChaosController(1, salt="")
    other = fi.ChaosController(1, salt="h3")
    assert victim.link_fault("10.0.0.1:5") is True
    for _ in range(5):
        assert driver.link_fault("10.0.0.1:5") is False
        assert other.link_fault("10.0.0.1:5") is False
    # Unnamed links never advance the named link's ordinal either.
    assert victim.link_fault("10.9.9.9:1") is False


def test_link_fault_gcs_label_and_driver_src(_link_env):
    """Rules name the head symbolically ('gcs') — whatever ephemeral
    port it bound — and 'driver' names the saltless launcher process."""
    fi.set_gcs_address("127.0.0.1:45678")
    GLOBAL_CONFIG.apply_system_config(
        {"chaos_partition_links": "driver>gcs@1+30.0"})
    driver = fi.ChaosController(7, salt="")
    hostd = fi.ChaosController(7, salt="h1")
    assert driver.link_fault("127.0.0.1:45678") is False  # ordinal 0
    assert driver.link_fault("127.0.0.1:45678") is True   # ordinal 1
    assert hostd.link_fault("127.0.0.1:45678") is False   # wrong src


def test_link_fault_malformed_rules_never_crash(_link_env):
    GLOBAL_CONFIG.apply_system_config(
        {"chaos_partition_links": "garbage;;h2>@+;h2>a:1@0+0.05"})
    c = fi.ChaosController(1, salt="h2")
    # Only the one well-formed rule parses and fires.
    assert c.link_fault("a:1") is True


# ---------------------------------------------------------------------------
# Unit: GcsClient outage ride-through
# ---------------------------------------------------------------------------

def test_gcs_client_rides_through_server_restart():
    """A control-plane call issued while the GCS is DOWN succeeds once a
    respawn binds the same port — buffered and retried inside the
    client, no error surfaced (tentpole piece 2)."""
    io = EventLoopThread("test-gcs-ride")
    server = RpcServer()
    served = []

    async def echo(req):
        served.append(req)
        return {"echo": req["x"]}

    server.register("Gcs", "Echo", echo)
    port = io.run(server.start(0))
    client = GcsClient(f"127.0.0.1:{port}")
    assert io.run(client.call("Gcs", "Echo", {"x": 1})) == {"echo": 1}
    io.run(server.stop())

    # "Supervised restart": the same port comes back after ~0.6s.
    server2 = RpcServer()
    server2.register("Gcs", "Echo", echo)

    def respawn():
        time.sleep(0.6)
        io.run(server2.start(port))

    t = threading.Thread(target=respawn, daemon=True)
    t.start()
    outages_before = _metric("gcs_outages")
    try:
        reply = io.run(client.call("Gcs", "Echo", {"x": 2}, timeout=5))
        assert reply == {"echo": 2}
        assert served[-1] == {"x": 2}
        # The outage was metered, not silent.
        assert _metric("gcs_outages") >= outages_before
    finally:
        t.join()
        io.run(client.close())
        io.run(server2.stop())
        io.stop()


def test_gcs_client_fail_fast_when_outage_retry_disabled():
    """outage_retry=False keeps fail-fast semantics for callers that
    MEASURE liveness (the hostd heartbeat loop): a dead head raises
    within the base retry budget instead of riding the deadline out."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    io = EventLoopThread("test-gcs-failfast")
    client = GcsClient(f"127.0.0.1:{port}")
    t0 = time.monotonic()
    try:
        with pytest.raises(Exception):
            io.run(client.call("Gcs", "heartbeat", {}, timeout=1.0,
                               outage_retry=False))
        # Way below gcs_outage_deadline_s (30s): it failed fast.
        assert time.monotonic() - t0 < 10.0
    finally:
        io.run(client.close())
        io.stop()


# ---------------------------------------------------------------------------
# Gate 1: serve traffic through a scripted GCS kill + supervised restart
# ---------------------------------------------------------------------------

@pytest.fixture
def serve_gcs_chaos_cluster(request):
    cfg = dict(getattr(request, "param", {}))
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    from ray_tpu import serve
    serve.start()
    try:
        yield info
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        from ray_tpu.serve import _private as sp
        with sp._router_states_lock:
            sp._router_states.clear()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


def _pump_gcs_ordinals(n, errors, stop=None, tail=40):
    """Drive the head's request ordinal toward the scripted kill point
    with cheap KV probes; each probe rides the driver's GcsClient, so
    the outage itself is absorbed here too.  With `stop`, pumping ends
    `tail` probes after it first returns True (the kill fired; the tail
    proves the restored head keeps serving control calls) — keeps the
    gates' wall time adaptive instead of always burning all n probes."""
    from ray_tpu import api as _api
    w = _api._worker
    extra = None
    for _ in range(n):
        try:
            w.io.run(w.gcs.call("Kv", "kv_exists",
                                {"ns": "chaos", "key": "pump"}))
        except Exception as e:  # noqa: BLE001 - the gate asserts on this
            errors.append(e)
        if stop is not None:
            if extra is None:
                if stop():
                    extra = tail
            else:
                extra -= 1
                if extra <= 0:
                    return


@pytest.mark.parametrize(
    "serve_gcs_chaos_cluster",
    [{"gcs_supervise": True,
      "chaos_enabled": True, "chaos_seed": 16,
      # Scripted: the first GCS incarnation ('gcs0') os._exit(1)s right
      # before serving its 500th control-plane request — mid-burst, with
      # serve traffic in flight.  The supervisor respawns 'gcs1' at the
      # same address from the sqlite tables; 'gcs1' is not in the default
      # salts list, so the cluster converges after exactly one kill.
      "chaos_kill_gcs_at": 500,
      "chaos_max_faults": 1}],
    indirect=True)
def test_serve_rides_through_scripted_gcs_kill(serve_gcs_chaos_cluster):
    """ISSUE acceptance gate: scripted GCS kill + supervised restart
    under live serve traffic — ZERO failed requests.  Routing is cached
    (stale-on-outage), requests flow peer-to-peer, and every control
    call buffers across the ~1s head outage."""
    from ray_tpu import api as _api
    from ray_tpu import serve

    @serve.deployment(name="head_ft", num_replicas=2,
                      max_concurrent_queries=8)
    def double(x):
        time.sleep(0.02)
        return 2 * x

    handle = serve.run(double.bind())
    assert handle.remote(1).result(timeout=60) == 2  # warm routing

    results, req_errors, pump_errors = [], [], []

    def one(i):
        try:
            results.append((i, handle.remote(i).result(timeout=120)))
        except Exception as e:  # noqa: BLE001 - the gate asserts on this
            req_errors.append(e)

    sup = _api._cluster["group"].supervisors[0]
    threads = [threading.Thread(target=one, args=(i,)) for i in range(30)]
    for t in threads:
        t.start()
        time.sleep(0.01)
    # Drive the head's ordinal past the scripted kill point while the
    # burst is in flight.
    _pump_gcs_ordinals(1000, pump_errors, stop=lambda: sup.restarts >= 1)
    for t in threads:
        t.join(180)

    deadline = time.monotonic() + 30
    while sup.restarts < 1 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert sup.restarts == 1, "the scripted GCS kill never fired"
    assert not req_errors, f"requests failed across the outage: {req_errors!r}"
    assert not pump_errors, f"control calls failed: {pump_errors!r}"
    assert sorted(results) == [(i, 2 * i) for i in range(30)]
    # The restored head serves NEW control-plane work (fresh actor).
    assert handle.remote(21).result(timeout=60) == 42


# ---------------------------------------------------------------------------
# Gate 2: training rides through the same kill loss-exact
# ---------------------------------------------------------------------------

@pytest.fixture
def gcs_chaos_cluster(request):
    cfg = dict(getattr(request, "param", {}))
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20,
                        _system_config=cfg)
    try:
        yield info
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


@pytest.mark.parametrize(
    "gcs_chaos_cluster",
    [{"gcs_supervise": True,
      "chaos_enabled": True, "chaos_seed": 16,
      "chaos_kill_gcs_at": 400,
      "chaos_max_faults": 1}],
    indirect=True)
def test_train_rides_through_scripted_gcs_kill_loss_exact(
        gcs_chaos_cluster):
    """ISSUE acceptance gate: the same scripted head kill under a
    training run — the gang never notices (steps flow worker-side, the
    driver's control calls buffer), NO recovery is burned, and the final
    weights are bit-exact with the unfaulted closed form."""
    import numpy as np

    from ray_tpu import api as _api
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import DataParallelTrainer

    N = 8

    def loop(config):
        import numpy as np
        from ray_tpu.train import session

        w = np.zeros(4)
        for step in range(N):
            w = w + (step + 1)
            time.sleep(0.3)
            session.report({"step": step, "w": w.tolist()})

    recoveries_before = _metric("train_recoveries", {"reason": "failure"})
    sup = _api._cluster["group"].supervisors[0]
    pump_errors = []

    def pump_late():
        time.sleep(1.5)  # let the gang form first
        _pump_gcs_ordinals(800, pump_errors, stop=lambda: sup.restarts >= 1)

    pt = threading.Thread(target=pump_late, daemon=True)
    pt.start()
    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=0)))
    result = trainer.fit()
    pt.join(120)

    deadline = time.monotonic() + 30
    while sup.restarts < 1 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert sup.restarts == 1, "the scripted GCS kill never fired"
    assert not pump_errors, f"control calls failed: {pump_errors!r}"
    # Loss-exact: max_failures=0 means any hiccup would have failed the
    # run; the history is complete and the weights match the closed form.
    assert result.error is None
    assert result.metrics["step"] == N - 1
    assert {m["step"] for m in result.metrics_history} == set(range(N))
    clean = np.zeros(4)
    for s in range(N):
        clean = clean + (s + 1)
    np.testing.assert_array_equal(np.asarray(result.metrics["w"]), clean)
    # No recovery was burned riding out the head outage.
    assert _metric("train_recoveries",
                   {"reason": "failure"}) == recoveries_before


# ---------------------------------------------------------------------------
# Gate 3: partition-then-heal fences the stale node
# ---------------------------------------------------------------------------

def test_partition_then_heal_fences_stale_node():
    """ISSUE acceptance gate: a sustained hostd->GCS blackhole gets the
    node declared dead and its actor failed over; when the link heals,
    the node's re-register is REFUSED (stale incarnation), it fences
    itself — killing the stale worker — and rejoins as incarnation 1,
    where the pending failover lands as a FRESH worker.  The op counts
    prove no double-apply: the replacement starts from clean state and
    the stale incarnation never serves again."""
    from ray_tpu._private import node as node_mod
    from ray_tpu.cluster_utils import Cluster

    base = node_mod._hostd_spawn_seq
    env = {
        # Fast liveness so the partition converts to node death quickly;
        # gcs.py reads these at import in the daemon processes.
        "RAY_TPU_HEARTBEAT_INTERVAL_S": "0.25",
        "RAY_TPU_NODE_DEATH_TIMEOUT_S": "2.0",
        "RAY_TPU_CHAOS_ENABLED": "1",
        "RAY_TPU_CHAOS_SEED": "16",
        # Scripted asymmetric partition: the SECOND hostd's outbound GCS
        # link blackholes at its 40th call (~5s in at 8 calls/s:
        # heartbeat + node-watch every 0.25s — well past actor setup)
        # for 4 seconds — double the 2s death timeout.  GCS->node and
        # worker links stay up: the stale worker keeps running, which is
        # the split-brain.
        "RAY_TPU_CHAOS_PARTITION_LINKS": f"h{base + 2}>gcs@40+4.0",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    GLOBAL_CONFIG.invalidate_cache()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    node2 = cluster.add_node(num_cpus=2, resources={"pin2": 1})
    try:
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.gcs_address)
        from ray_tpu import api as _api
        w = _api._worker

        @ray_tpu.remote(max_restarts=2, max_task_retries=-1,
                        resources={"pin2": 0.5})
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return (os.getpid(), self.n)

        c = Counter.remote()
        pid1, v = ray_tpu.get(c.inc.remote(), timeout=60)
        assert v == 1
        for expect in (2, 3):
            p, v = ray_tpu.get(c.inc.remote(), timeout=30)
            assert (p, v) == (pid1, expect)

        def node2_info():
            reply = w.io.run(w.gcs.call("Gcs", "get_nodes", {}, timeout=10))
            for n in reply["nodes"]:
                if n.node_id.hex() == node2["node_id"]:
                    return n
            return None

        # Phase 1: the partition opens and the head declares node2 dead.
        # Generous deadlines throughout: every phase is a wait-until on
        # heartbeat/fence timers that stretch under CI load — the loops
        # exit as soon as the condition lands, so a wide window costs
        # nothing on a healthy box and only absorbs scheduler noise.
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            info = node2_info()
            if info is not None and not info.alive:
                break
            time.sleep(0.25)
        assert info is not None and not info.alive, \
            "partition never got node2 declared dead"
        # Split-brain window: the stale worker is still running (the
        # partition only cut the hostd's control link).
        try:
            os.kill(pid1, 0)
        except OSError:
            pytest.fail("stale worker died before fencing — no split brain")

        # Phase 2: the link heals, the node fences itself and rejoins as
        # the next incarnation.
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            info = node2_info()
            if info is not None and info.alive and \
                    int(getattr(info, "incarnation", 0)) >= 1:
                break
            time.sleep(0.25)
        assert info is not None and info.alive, "node2 never rejoined"
        assert int(getattr(info, "incarnation", 0)) == 1, \
            "rejoin did not bump the node incarnation"

        # Phase 3: the failover lands back on the healed node as a FRESH
        # worker; the stale incarnation is dead and its state is gone.
        deadline = time.monotonic() + 90
        pid2 = None
        while time.monotonic() < deadline:
            try:
                pid2, v = ray_tpu.get(c.inc.remote(), timeout=10)
                break
            except Exception:
                time.sleep(0.5)
        assert pid2 is not None, "actor never came back after the heal"
        assert pid2 != pid1, "failover reused the fenced worker"
        # Fresh state (the __init__ re-ran): counting restarts at 1, and
        # subsequent ops apply exactly once, in order.
        assert v == 1
        for expect in (2, 3):
            p, v = ray_tpu.get(c.inc.remote(), timeout=30)
            assert (p, v) == (pid2, expect)
        # The stale worker was killed by the fence, not left running.
        fence_deadline = time.monotonic() + 60
        while time.monotonic() < fence_deadline:
            try:
                os.kill(pid1, 0)
                time.sleep(0.25)
            except OSError:
                break
        with pytest.raises(OSError):
            os.kill(pid1, 0)
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            GLOBAL_CONFIG.invalidate_cache()
            fi.reset()


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v", "-x"]))
