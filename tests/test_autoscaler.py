"""Autoscaler tests: bin-packing decisions (unit, mocked state) and the
end-to-end fake-provider flow where a pending placement group triggers a
real scale-up and then schedules.

Reference coverage model: python/ray/tests/test_autoscaler.py (mocked
NodeProvider unit tests) + test_autoscaler_fake_multinode.py (e2e with the
fake provider).
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    FakeNodeProvider,
    NodeTypeConfig,
    ResourceDemandScheduler,
    StandardAutoscaler,
)
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)


# ---------------------------------------------------------------------------
# Unit: bin-packing
# ---------------------------------------------------------------------------


def _sched(**types):
    return ResourceDemandScheduler(
        {name: NodeTypeConfig(name, res, max_workers=mw,
                              slice_hosts=sh)
         for name, (res, mw, sh) in types.items()})


def test_binpack_launches_for_flat_demand():
    s = _sched(cpu=({"CPU": 4.0}, 10, 1))
    plan = s.get_nodes_to_launch(
        existing=[{"CPU": 1.0}], existing_counts={},
        demands=[{"CPU": 2.0}, {"CPU": 2.0}, {"CPU": 2.0}],
        pg_demands=[])
    assert plan == {"cpu": 2}  # 3x2 CPU, one node packs two demands


def test_binpack_respects_max_workers():
    s = _sched(cpu=({"CPU": 1.0}, 2, 1))
    plan = s.get_nodes_to_launch(
        existing=[], existing_counts={"cpu": 1},
        demands=[{"CPU": 1.0}] * 5, pg_demands=[])
    assert plan == {"cpu": 1}  # cap 2, one already exists


def test_binpack_pg_gang_semantics():
    s = _sched(cpu=({"CPU": 4.0}, 10, 1))
    plan = s.get_nodes_to_launch(
        existing=[{"CPU": 4.0}], existing_counts={"cpu": 1},
        demands=[],
        pg_demands=[("STRICT_SPREAD", [{"CPU": 2.0}] * 3)])
    # One bundle fits the existing node; STRICT_SPREAD needs 3 hosts total.
    assert plan == {"cpu": 3}


def test_binpack_tpu_slice_is_atomic():
    """A v5p-style slice scales in whole-slice host multiples (SURVEY P1)."""
    s = _sched(slice=({"CPU": 100.0, "TPU": 4.0}, 64, 4))
    plan = s.get_nodes_to_launch(
        existing=[], existing_counts={},
        demands=[],
        pg_demands=[("PACK", [{"TPU": 4.0}] * 2)])  # 2 hosts of demand
    assert plan == {"slice": 4}  # rounded up to one whole 4-host slice

    plan = s.get_nodes_to_launch(
        existing=[], existing_counts={},
        demands=[{"TPU": 4.0}] * 5, pg_demands=[])
    assert plan["slice"] % 4 == 0 and plan["slice"] >= 8


def test_binpack_infeasible_type_not_chosen():
    s = _sched(small=({"CPU": 2.0}, 10, 1), big=({"CPU": 16.0}, 10, 1))
    plan = s.get_nodes_to_launch(
        existing=[], existing_counts={},
        demands=[{"CPU": 8.0}], pg_demands=[])
    assert plan == {"big": 1}


# ---------------------------------------------------------------------------
# End-to-end: pending PG -> scale-up -> PG schedules
# ---------------------------------------------------------------------------


def test_autoscaler_scales_up_for_pending_pg():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    try:
        ray_tpu.init(address=cluster.address)
        provider = FakeNodeProvider(cluster, {
            "cpu-worker": NodeTypeConfig("cpu-worker", {"CPU": 2.0},
                                         max_workers=4),
        })
        autoscaler = StandardAutoscaler(
            provider, provider.node_types, cluster.address,
            idle_timeout_s=3600)

        # A 2-host gang the 1-node cluster cannot satisfy.
        pg = placement_group([{"CPU": 2.0}, {"CPU": 2.0}],
                             strategy="STRICT_SPREAD")
        assert not pg.wait(2), "PG should pend before scale-up"

        launched = autoscaler.update()
        assert sum(launched.values()) >= 1, "expected a scale-up decision"
        assert pg.wait(60), "PG must schedule after scale-up"
        remove_placement_group(pg)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_autoscaler_scales_down_idle_nodes():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    try:
        ray_tpu.init(address=cluster.address)
        provider = FakeNodeProvider(cluster, {
            "cpu-worker": NodeTypeConfig("cpu-worker", {"CPU": 2.0},
                                         max_workers=4),
        })
        autoscaler = StandardAutoscaler(
            provider, provider.node_types, cluster.address,
            idle_timeout_s=0.5)
        provider.create_nodes("cpu-worker", 1)
        assert len(provider.non_terminated_nodes()) == 1

        autoscaler.update()          # records idle t0
        time.sleep(0.8)
        autoscaler.update()          # past idle timeout -> terminate
        assert len(provider.non_terminated_nodes()) == 0
        assert autoscaler.terminated_total == 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
