"""C++ embedding API test (reference: the role of cpp/ — native programs
interoperating with the cluster; see cpp/include/ray_tpu/store_client.hpp
for the documented scope decision): a C++ program attaches to a
Python-created store, writes an object, and Python reads it zero-copy —
and vice versa."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cpp_binary(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("cppbin") / "roundtrip")
    src = str(tmp_path_factory.mktemp("cppsrc") / "roundtrip.cc")
    with open(src, "w") as f:
        f.write(r'''
#include <cstdio>
#include <cstring>
#include <ray_tpu/store_client.hpp>

// argv: <store path> <28-byte hex id to read> <28-byte hex id to write>
static ray_tpu::ObjectId from_hex(const char* hx) {
  std::string b;
  for (int i = 0; i < ray_tpu::kObjectIdSize; i++) {
    unsigned v;
    sscanf(hx + 2 * i, "%2x", &v);
    b.push_back(char(v));
  }
  return ray_tpu::ObjectId::from_binary(b);
}

int main(int argc, char** argv) {
  auto store = ray_tpu::Store::attach(argv[1]);
  // Read the object Python wrote; double every byte into a new object.
  auto buf = store.get(from_hex(argv[2]), 5000);
  auto out_id = from_hex(argv[3]);
  uint8_t* dst = store.create(out_id, buf.size());
  for (uint64_t i = 0; i < buf.size(); i++)
    dst[i] = uint8_t(buf.data()[i] * 2);
  store.seal(out_id);
  std::printf("ok %llu\n", (unsigned long long)buf.size());
  return 0;
}
''')
    proc = subprocess.run(
        ["g++", "-std=c++17", "-O2", "-I", os.path.join(REPO, "cpp/include"),
         src, os.path.join(REPO, "ray_tpu/_native/objstore.cc"),
         "-pthread", "-o", out],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return out


def test_cpp_store_roundtrip(cpp_binary, tmp_path):
    import numpy as np

    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import ObjectStore

    store = ObjectStore.create(str(tmp_path / "store.shm"), 32 << 20)
    try:
        in_id = ObjectID.from_random()
        out_id = ObjectID.from_random()
        payload = np.arange(100, dtype=np.uint8)
        store.put_bytes(in_id, payload.tobytes())

        proc = subprocess.run(
            [cpp_binary, store.path, in_id.hex(), out_id.hex()],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.startswith("ok 100")

        buf = store.get(out_id, timeout_ms=5000)
        try:
            got = np.frombuffer(bytes(buf.data), np.uint8)
        finally:
            buf.release()
        np.testing.assert_array_equal(got, (payload * 2).astype(np.uint8))
    finally:
        store.close()
