"""Cross-node object transfer tests (reference: object_manager/ chunked
push/pull with in-flight throttling)."""

import pytest

import ray_tpu


def test_chunked_cross_node_transfer():
    """A >chunk-size object pulls across nodes as bounded-concurrency
    chunks (reference: object_manager chunked push/pull)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "object_store_memory": 96 << 20})
    try:
        cluster.add_node(num_cpus=2, object_store_memory=96 << 20)
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.address)

        blob = np.arange(24 << 20, dtype=np.uint8) % 199  # 24MB = 3 chunks

        @ray_tpu.remote(num_cpus=2)
        def produce():
            return blob

        @ray_tpu.remote(num_cpus=2)
        def consume(x):
            return int(x.sum()), x.shape[0]

        # Producer and consumer each demand 2 CPUs: they land on different
        # nodes, so the arg crosses the node boundary.
        ref = produce.remote()
        ray_tpu.wait([ref], num_returns=1, timeout=60, fetch_local=False)
        total, n = ray_tpu.get(consume.remote(ref), timeout=120)
        assert n == 24 << 20
        assert total == int(blob.sum())

        # Deterministic chunked-path check: pull the big object from its
        # hosting node via the chunk protocol directly.
        from ray_tpu import api
        w = api._worker
        big_ref = ray_tpu.put(blob)
        st = w.objects[big_ref.id]
        (loc,) = tuple(st.locations)
        nodes = w.io.run(w._node_table())
        fetched = w.io.run(w._pull_from_node(nodes[loc], big_ref.id))
        assert fetched is not None
        data, _meta = fetched
        assert len(data) > w.PULL_CHUNK_BYTES  # really took the chunk path
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_lineage_reconstruction_after_node_death():
    """Chaos: the node holding a task's (store-resident) result dies; the
    owner re-executes the producing task from lineage and get() succeeds
    (reference: object_recovery_manager.h:41 + NodeKillerActor chaos
    tests)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1,
                                      "object_store_memory": 96 << 20})
    try:
        victim = cluster.add_node(num_cpus=2,
                                  object_store_memory=96 << 20)
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote(num_cpus=2, max_retries=3)
        def produce(tag):
            import os
            return np.full(1 << 20, 7, np.uint8), os.getpid()

        # num_cpus=2 only fits the victim node: the result lives in ITS
        # store (1MB > inline limit).
        ref = produce.remote("x")
        ray_tpu.wait([ref], num_returns=1, timeout=60, fetch_local=False)

        cluster.remove_node(victim)  # hard kill: store contents gone

        # A fresh 2-CPU node lets the reconstructed task schedule.
        cluster.add_node(num_cpus=2, object_store_memory=96 << 20)
        cluster.wait_for_nodes()

        arr, pid = ray_tpu.get(ref, timeout=120)
        assert arr.shape == (1 << 20,) and int(arr[0]) == 7
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_native_transfer_plane(tmp_path):
    """The C++ data plane (objtransfer.cc) moves an object between two
    stores shm-to-shm: server serves from its mmap, client receives into
    an unsealed allocation and seals (reference: object_manager/ bulk
    payload path)."""
    import os

    import numpy as np

    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import ObjectStore
    from ray_tpu._private.object_transfer import TransferServer, fetch

    a_path, b_path = str(tmp_path / "a.shm"), str(tmp_path / "b.shm")
    a = ObjectStore.create(a_path, 64 << 20)
    b = ObjectStore.create(b_path, 64 << 20)
    srv = TransferServer(a_path)
    try:
        oid = ObjectID(os.urandom(28))
        payload = (np.arange(20 << 20, dtype=np.uint8) % 251).tobytes()
        a.put_bytes(oid, payload, b"meta!")

        assert fetch(b_path, "127.0.0.1", srv.port, oid)
        buf = b.get(oid)
        assert buf is not None
        assert bytes(buf.data) == payload
        assert buf.metadata == b"meta!"
        buf.release()

        # already-local fetch reports success (EXISTS)
        assert fetch(b_path, "127.0.0.1", srv.port, oid)
        # remote miss reports False, store untouched
        missing = ObjectID(os.urandom(28))
        assert not fetch(b_path, "127.0.0.1", srv.port, missing)
        assert not b.contains(missing)
    finally:
        srv.close()
        a.close()
        b.close()
