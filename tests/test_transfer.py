"""Cross-node object transfer tests (reference: object_manager/ chunked
push/pull with in-flight throttling)."""

import pytest

import ray_tpu


def test_chunked_cross_node_transfer():
    """A >chunk-size object pulls across nodes as bounded-concurrency
    chunks (reference: object_manager chunked push/pull)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "object_store_memory": 96 << 20})
    try:
        cluster.add_node(num_cpus=2, object_store_memory=96 << 20)
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.address)

        blob = np.arange(24 << 20, dtype=np.uint8) % 199  # 24MB = 3 chunks

        @ray_tpu.remote(num_cpus=2)
        def produce():
            return blob

        @ray_tpu.remote(num_cpus=2)
        def consume(x):
            return int(x.sum()), x.shape[0]

        # Producer and consumer each demand 2 CPUs: they land on different
        # nodes, so the arg crosses the node boundary.
        ref = produce.remote()
        ray_tpu.wait([ref], num_returns=1, timeout=60, fetch_local=False)
        total, n = ray_tpu.get(consume.remote(ref), timeout=120)
        assert n == 24 << 20
        assert total == int(blob.sum())

        # Deterministic chunked-path check: pull the big object from its
        # hosting node via the chunk protocol directly.
        from ray_tpu import api
        w = api._worker
        big_ref = ray_tpu.put(blob)
        st = w.objects[big_ref.id]
        (loc,) = tuple(st.locations)
        nodes = w.io.run(w._node_table())
        fetched = w.io.run(w._pull_from_node(nodes[loc], big_ref.id))
        assert fetched is not None
        data, _meta = fetched
        assert len(data) > w.PULL_CHUNK_BYTES  # really took the chunk path
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
