"""Typed proto contract tests (reference: src/ray/protobuf/ — typed RPC
contracts; here routed over the string-routed transport with a proto
payload marker)."""

import asyncio

import pytest

from ray_tpu import protocol
from ray_tpu.protocol import pb


def test_encode_decode_roundtrip():
    m = pb.PullObjectMetaReply(found=True, data_size=123,
                               metadata=b"\x00meta", spilled=False,
                               transfer_port=40001)
    out = protocol.decode(protocol.encode(m))
    assert out.found and out.data_size == 123
    assert out.metadata == b"\x00meta"
    assert out.transfer_port == 40001


def test_decode_unknown_message_rejected():
    blob = bytes([7]) + b"Unknown" + b"xxxx"
    with pytest.raises(ValueError):
        protocol.decode(blob)


def test_rpc_carries_proto_messages_without_pickle():
    """A proto request/reply rides the transport under the \\x03 marker —
    the wire payload is protobuf, not pickle."""
    from ray_tpu._private import rpc as rpc_mod
    from ray_tpu._private.rpc import RpcClient, RpcServer

    # The marker encoding must keep proto distinct from raw/pickle.
    wire = rpc_mod._dumps(pb.HeartbeatRequest(node_id=b"n" * 20))
    assert wire[:1] == rpc_mod._PB
    assert b"pickle" not in wire

    async def main():
        server = RpcServer("127.0.0.1")
        seen = {}

        async def handler(req):
            assert isinstance(req, pb.HeartbeatRequest)
            seen["node"] = req.node_id
            return pb.HeartbeatReply(shutdown=False, reregister=True)

        server.register("Gcs", "HeartbeatP", handler)
        port = await server.start(0)
        client = RpcClient(f"127.0.0.1:{port}")
        try:
            reply = await client.call(
                "Gcs", "HeartbeatP",
                pb.HeartbeatRequest(node_id=b"n" * 20), timeout=10)
            assert isinstance(reply, pb.HeartbeatReply)
            assert reply.reregister and not reply.shutdown
            assert seen["node"] == b"n" * 20
        finally:
            await client.close()
            await server.stop()

    asyncio.run(main())


def test_object_plane_rides_proto(tmp_path):
    """The hostd object-plane methods accept and emit typed messages
    end-to-end through a live cluster (PullObjectMeta probe)."""
    import numpy as np

    import ray_tpu
    from ray_tpu import api

    ray_tpu.init(num_cpus=2, object_store_memory=64 << 20)
    try:
        ref = ray_tpu.put(np.arange(1 << 20, dtype=np.uint8))
        w = api._worker
        st = w.objects[ref.id]
        (loc,) = tuple(st.locations)
        nodes = w.io.run(w._node_table())

        async def probe():
            client = w.pool.get(nodes[loc])
            return await client.call(
                "NodeManager", "PullObjectMeta",
                pb.PullObjectMetaRequest(id=ref.id.binary()))

        reply = w.io.run(probe())
        assert isinstance(reply, pb.PullObjectMetaReply)
        assert reply.found and reply.data_size > 1 << 20
        # When the native transfer lib builds here, the hostd (same image)
        # must be serving it; 0 is legitimate only if the lib is absent.
        from ray_tpu._private import object_transfer
        try:
            object_transfer._load()
            native = True
        except Exception:
            native = False
        if native:
            assert reply.transfer_port > 0
    finally:
        ray_tpu.shutdown()


def test_taskspec_proto_roundtrip():
    """The typed TaskSpecP contract (reference: common.proto TaskSpec)
    round-trips the runtime's internal spec losslessly — the encoding a
    non-Python submitter speaks."""
    from ray_tpu.protocol import convert, decode, encode
    from ray_tpu._private.ids import ActorID, JobID, TaskID
    from ray_tpu._private.protocol import (
        RefArg,
        Resources,
        TaskSpec,
        ValueArg,
    )

    jid = JobID(b"\x01\x00\x00\x00")
    spec = TaskSpec(
        task_id=TaskID.of(), job_id=jid, name="train_step",
        fn_key="fn:abc123",
        args=[ValueArg(b"\x80\x05data", b"meta"),
              RefArg(b"r" * 28, "10.0.0.1:4444")],
        kwargs={"lr": ValueArg(b"\x80\x05lr", b"")},
        num_returns=2,
        resources=Resources(cpu=2.0, tpu=1.0, memory=1e9,
                            custom={"accelerator_type:v5e": 0.001}),
        max_retries=5, retry_exceptions=True,
        owner_address="10.0.0.2:5555",
        actor_id=ActorID.of(jid), method_name="step",
        max_concurrency=4, scheduling_strategy="SPREAD",
        bundle_index=1,
        runtime_env={"env_vars": {"A": "1"},
                     "pip": {"packages": ["x"], "wheelhouse": "/wh"}},
    )
    spec.seq_no = 77
    m = convert.taskspec_to_proto(spec)
    # Through the wire framing too (registry encode/decode).
    m2 = decode(encode(m))
    back = convert.taskspec_from_proto(m2)
    assert back.task_id == spec.task_id and back.job_id == spec.job_id
    assert back.name == spec.name and back.fn_key == spec.fn_key
    assert isinstance(back.args[0], ValueArg)
    assert back.args[0].data == b"\x80\x05data"
    assert isinstance(back.args[1], RefArg)
    assert back.args[1].owner_address == "10.0.0.1:4444"
    assert back.kwargs["lr"].data == b"\x80\x05lr"
    assert back.num_returns == 2 and back.max_retries == 5
    assert back.retry_exceptions and back.actor_id == spec.actor_id
    assert back.method_name == "step" and back.seq_no == 77
    assert back.resources.cpu == 2.0 and back.resources.tpu == 1.0
    assert back.resources.custom == {"accelerator_type:v5e": 0.001}
    assert back.scheduling_strategy == "SPREAD" and back.bundle_index == 1
    assert back.runtime_env == spec.runtime_env


def test_lease_and_kv_messages_roundtrip():
    from ray_tpu.protocol import decode, encode, pb

    req = pb.RequestWorkerLeaseRequest(job_id=3, pg_hex="", tpu=True)
    req.resources.amounts["TPU"] = 1.0
    out = decode(encode(req))
    assert out.tpu and out.resources.amounts["TPU"] == 1.0
    kv = decode(encode(pb.KvPutRequest(ns="fn", key="k", value=b"v",
                                       overwrite=True)))
    assert kv.ns == "fn" and kv.value == b"v"
