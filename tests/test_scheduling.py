"""Scheduling-policy and option-surface tests (reference:
raylet/scheduling/policy/* + scheduling_policy_test.cc's fake-snapshot
style, _private/ray_option_utils.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import scheduler as sched
from ray_tpu._private.ids import NodeID
from ray_tpu._private.protocol import NodeInfo, Resources


def _node(i, cpu_total=4.0, cpu_avail=None, extra=None):
    total = {"CPU": cpu_total, **(extra or {})}
    avail = dict(total)
    if cpu_avail is not None:
        avail["CPU"] = cpu_avail
    return NodeInfo(node_id=NodeID(bytes([i]) * 20), address=f"n{i}:1",
                    hostname=f"h{i}", store_path="",
                    resources_total=total, resources_available=avail)


def test_random_policy_uniform_over_feasible():
    nodes = [_node(1), _node(2), _node(3, cpu_avail=0.0, cpu_total=0.0)]
    seen = set()
    for _ in range(50):
        n = sched.pick_node(nodes, {"CPU": 1}, strategy="RANDOM")
        seen.add(n.address)
    assert seen == {"n1:1", "n2:1"}  # infeasible node never chosen


def test_locality_prefers_arg_holding_node():
    nodes = [_node(1), _node(2)]
    loc = {nodes[1].node_id.hex(): 3}
    n = sched.pick_node(nodes, {"CPU": 1}, locality=loc)
    assert n.address == "n2:1"
    # Saturated holder: locality must NOT pin the task to a full node.
    nodes2 = [_node(1), _node(2, cpu_avail=0.0)]
    n = sched.pick_node(nodes2, {"CPU": 1}, locality=loc)
    assert n.address == "n1:1"


def test_accelerator_type_demand_routes_to_advertising_node():
    r = Resources.from_options({"accelerator_type": "TPU-V5E"})
    assert r.to_dict()["accelerator_type:TPU-V5E"] == 0.001
    plain, tpu_node = _node(1), _node(
        2, extra={"accelerator_type:TPU-V5E": 1.0})
    n = sched.pick_node([plain, tpu_node], r.to_dict())
    assert n.address == "n2:1"
    # No advertising node at all -> infeasible.
    assert sched.pick_node([plain], r.to_dict()) is None


def test_memory_resource_schedules_and_gates():
    nodes = [_node(1, extra={"memory": 1000.0})]
    assert sched.pick_node(nodes, {"CPU": 1, "memory": 800.0}) is not None
    assert sched.pick_node(nodes, {"CPU": 1, "memory": 2000.0}) is None


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_memory_option_end_to_end(cluster):
    """Nodes advertise detected memory; a memory-demanding task runs."""

    @ray_tpu.remote(num_cpus=1, memory=64 << 20)
    def f():
        return "ran"

    assert ray_tpu.get(f.remote(), timeout=60) == "ran"


def test_multiprocessing_pool_shim(cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(lambda x: x * x, range(10)) == [
            x * x for x in range(10)]
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(lambda a, b=0: a - b, (10,), {"b": 4}) == 6
        res = pool.apply_async(lambda: 42)
        assert res.get(timeout=60) == 42
        assert sorted(pool.imap_unordered(lambda x: -x, range(5))) == [
            -4, -3, -2, -1, 0]
        assert list(pool.imap(lambda x: x + 1, range(5))) == [1, 2, 3, 4, 5]
    with pytest.raises(ValueError):
        pool.map(lambda x: x, [1])


def test_dependency_chain_on_cold_workers_no_deadlock(cluster):
    """Dependency-gated dispatch (reference: raylet dependency manager):
    a consumer whose producer is still pending must not be pushed into a
    worker FIFO ahead of that producer — with inline per-worker
    execution that ordering deadlocked both tasks.  Exercise many
    producer->consumer chains submitted back-to-back so cold-worker
    discovery races would have scrambled dispatch order."""
    import numpy as np

    @ray_tpu.remote
    def produce(n):
        return np.ones(n)

    @ray_tpu.remote
    def consume(arr):
        return float(np.asarray(arr).sum())

    chains = [consume.remote(produce.remote(10 * (i + 1)))
              for i in range(12)]
    # Deep chain too: each stage depends on the previous.
    x = produce.remote(7)
    for _ in range(5):
        # consume(scalar) sums a 0-d array: value stays 7.0 while each
        # stage depends on the previous one's pending output.
        x = consume.remote(x)
    deep = consume.remote(x)
    out = ray_tpu.get(chains + [deep], timeout=120)
    assert out[:12] == [10.0 * (i + 1) for i in range(12)]
    assert out[-1] == 7.0


def test_batched_dispatch_semantics(cluster):
    """Batched lease grants amortize the control plane without changing
    task semantics: a burst of N same-key tasks costs far fewer GRANTED
    lease RPCs than N (each RPC carries a count and may grant several
    workers in one reply), every task keeps its own result or error,
    and the trace still carries one dispatch + one exec span per task."""
    import time

    from ray_tpu import state
    from ray_tpu.exceptions import TaskError
    from ray_tpu.util import events as ev
    from ray_tpu.util import tracing
    from ray_tpu._private.config import GLOBAL_CONFIG

    @ray_tpu.remote
    def batched(i):
        if i == 13:
            raise ValueError(f"task {i} boom")
        return i * 2

    n = 64
    batch = max(1, GLOBAL_CONFIG.sched_batch_max)
    # Quiesce: leases held over from earlier tests in this module would
    # serve the burst without a single new lease RPC (reuse is the
    # point of the pool, but this test must observe acquisition).  Held
    # leases are returned after lease_idle_ttl_s of idleness.
    time.sleep(GLOBAL_CONFIG.lease_idle_ttl_s + 1.5)
    t0 = time.time()
    with tracing.trace("batched_dispatch") as tid:
        refs = [batched.remote(i) for i in range(n)]
        # Per-task errors: exactly the poisoned task fails, nobody else.
        with pytest.raises(TaskError, match="task 13 boom"):
            ray_tpu.get(refs[13], timeout=60)
        got = ray_tpu.get(refs[:13] + refs[14:], timeout=120)
    assert got == [i * 2 for i in range(n) if i != 13]

    # Lease amortization: the driver ring records one sched/lease_wait
    # span per LeaseWorker RPC.  Count the granted ones (busy probes
    # while the queue drains through held leases are retried/swallowed
    # and don't grant anything).
    rec = ev.get_recorder()
    assert rec is not None
    ends = [e for e in rec.snapshot(since=t0, plane="sched",
                                    kind="lease_wait")
            if (e["payload"] or {}).get("ph") == "E"
            and (e["payload"] or {}).get("granted")]
    assert ends, "no granted lease RPC recorded"
    # A hard ceil(n / batch) bound would be wrong twice over: the 4-CPU
    # node caps any one reply at 4 grants, and an idle lease returned
    # mid-run re-leases through an extra granted RPC under CPU
    # contention.  What batching actually guarantees: granted-RPC count
    # is a function of lease churn (leases are reused task after task),
    # not of task count — far fewer RPCs than tasks.
    assert len(ends) <= n // 4, (
        f"{len(ends)} granted lease RPCs for {n} tasks (batch={batch})")

    # The multi-grant reply itself is checked deterministically against
    # the hostd: the e2e burst above may legitimately satisfy itself
    # with count=1 requests whenever the pump keeps pace with the
    # submit loop, so observing a batched grant there is a race.  One
    # LeaseWorker RPC carrying count=3 on a quiesced node must collect
    # several workers in a single reply.
    from ray_tpu import api as _api

    cw = _api._worker
    # Quiesce for real: the driver's reaper returns idle leases lazily
    # (spread over a few ticks past the TTL), so poll the hostd's
    # worker table until no lease is held instead of sleeping a guess.
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        table = cw.io.run(cw.pool.get(cw.hostd_address).call(
            "NodeManager", "ListWorkers", {}))
        if not any(w["state"] == "leased" for w in table["workers"]):
            break
        time.sleep(0.2)
    reply = cw.io.run(cw.pool.get(cw.hostd_address).call(
        "NodeManager", "LeaseWorker",
        {"resources": {"CPU": 1}, "job_id": cw._job_int(),
         "runtime_env": None, "count": 3}, timeout=60))
    try:
        assert reply.get("granted"), reply
        assert len(reply.get("grants", [])) >= 2, (
            f"count=3 lease reply carried "
            f"{len(reply.get('grants', []))} grant(s)")
    finally:
        for g in reply.get("grants", []):
            cw.io.run(cw.pool.get(cw.hostd_address).call(
                "NodeManager", "ReturnWorker",
                {"lease_id": g["lease_id"]}))

    # Trace integrity: batching must not merge per-task spans.
    time.sleep(0.5)
    tree = state.spans(tid)
    kinds = {}
    for rec_ in tree["spans"]:
        kinds[rec_["kind"]] = kinds.get(rec_["kind"], 0) + 1
    assert kinds.get("dispatch", 0) == n
    assert kinds.get("task", 0) == n
