"""Scheduling-policy and option-surface tests (reference:
raylet/scheduling/policy/* + scheduling_policy_test.cc's fake-snapshot
style, _private/ray_option_utils.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import scheduler as sched
from ray_tpu._private.ids import NodeID
from ray_tpu._private.protocol import NodeInfo, Resources


def _node(i, cpu_total=4.0, cpu_avail=None, extra=None):
    total = {"CPU": cpu_total, **(extra or {})}
    avail = dict(total)
    if cpu_avail is not None:
        avail["CPU"] = cpu_avail
    return NodeInfo(node_id=NodeID(bytes([i]) * 20), address=f"n{i}:1",
                    hostname=f"h{i}", store_path="",
                    resources_total=total, resources_available=avail)


def test_random_policy_uniform_over_feasible():
    nodes = [_node(1), _node(2), _node(3, cpu_avail=0.0, cpu_total=0.0)]
    seen = set()
    for _ in range(50):
        n = sched.pick_node(nodes, {"CPU": 1}, strategy="RANDOM")
        seen.add(n.address)
    assert seen == {"n1:1", "n2:1"}  # infeasible node never chosen


def test_locality_prefers_arg_holding_node():
    nodes = [_node(1), _node(2)]
    loc = {nodes[1].node_id.hex(): 3}
    n = sched.pick_node(nodes, {"CPU": 1}, locality=loc)
    assert n.address == "n2:1"
    # Saturated holder: locality must NOT pin the task to a full node.
    nodes2 = [_node(1), _node(2, cpu_avail=0.0)]
    n = sched.pick_node(nodes2, {"CPU": 1}, locality=loc)
    assert n.address == "n1:1"


def test_accelerator_type_demand_routes_to_advertising_node():
    r = Resources.from_options({"accelerator_type": "TPU-V5E"})
    assert r.to_dict()["accelerator_type:TPU-V5E"] == 0.001
    plain, tpu_node = _node(1), _node(
        2, extra={"accelerator_type:TPU-V5E": 1.0})
    n = sched.pick_node([plain, tpu_node], r.to_dict())
    assert n.address == "n2:1"
    # No advertising node at all -> infeasible.
    assert sched.pick_node([plain], r.to_dict()) is None


def test_memory_resource_schedules_and_gates():
    nodes = [_node(1, extra={"memory": 1000.0})]
    assert sched.pick_node(nodes, {"CPU": 1, "memory": 800.0}) is not None
    assert sched.pick_node(nodes, {"CPU": 1, "memory": 2000.0}) is None


@pytest.fixture(scope="module")
def cluster():
    info = ray_tpu.init(num_cpus=4, object_store_memory=64 << 20)
    yield info
    ray_tpu.shutdown()


def test_memory_option_end_to_end(cluster):
    """Nodes advertise detected memory; a memory-demanding task runs."""

    @ray_tpu.remote(num_cpus=1, memory=64 << 20)
    def f():
        return "ran"

    assert ray_tpu.get(f.remote(), timeout=60) == "ran"


def test_multiprocessing_pool_shim(cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(lambda x: x * x, range(10)) == [
            x * x for x in range(10)]
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(lambda a, b=0: a - b, (10,), {"b": 4}) == 6
        res = pool.apply_async(lambda: 42)
        assert res.get(timeout=60) == 42
        assert sorted(pool.imap_unordered(lambda x: -x, range(5))) == [
            -4, -3, -2, -1, 0]
        assert list(pool.imap(lambda x: x + 1, range(5))) == [1, 2, 3, 4, 5]
    with pytest.raises(ValueError):
        pool.map(lambda x: x, [1])


def test_dependency_chain_on_cold_workers_no_deadlock(cluster):
    """Dependency-gated dispatch (reference: raylet dependency manager):
    a consumer whose producer is still pending must not be pushed into a
    worker FIFO ahead of that producer — with inline per-worker
    execution that ordering deadlocked both tasks.  Exercise many
    producer->consumer chains submitted back-to-back so cold-worker
    discovery races would have scrambled dispatch order."""
    import numpy as np

    @ray_tpu.remote
    def produce(n):
        return np.ones(n)

    @ray_tpu.remote
    def consume(arr):
        return float(np.asarray(arr).sum())

    chains = [consume.remote(produce.remote(10 * (i + 1)))
              for i in range(12)]
    # Deep chain too: each stage depends on the previous.
    x = produce.remote(7)
    for _ in range(5):
        # consume(scalar) sums a 0-d array: value stays 7.0 while each
        # stage depends on the previous one's pending output.
        x = consume.remote(x)
    deep = consume.remote(x)
    out = ray_tpu.get(chains + [deep], timeout=120)
    assert out[:12] == [10.0 * (i + 1) for i in range(12)]
    assert out[-1] == 7.0
