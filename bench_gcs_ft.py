"""Head-availability benchmark: control-plane survival under a scripted
GCS kill, with and without the supervised restart.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Runs the same probe workload twice against a cluster whose GCS
`os._exit(1)`s at a scripted request ordinal (`chaos_kill_gcs_at`):
once with `gcs_supervise` on (the launcher respawns the head at the
same address from its sqlite tables; clients buffer-and-retry across
the outage) and once with it off (the head stays dead).  Each probe
round issues one control-plane call (KV probe through the GcsClient)
and one data-plane call (an actor method, peer-to-peer) so the two
planes' availability decouple: the data plane should ride out a head
death in BOTH modes — that is the architectural claim — while
control-plane availability is what supervision buys.

`value` is supervised control-plane availability; `vs_baseline` is the
ratio over the unsupervised run.  p99 control latency rides along so
the ride-through cost (buffered calls during the respawn) is visible.
"""

from __future__ import annotations

import argparse
import json
import threading
import time


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _run_mode(args, supervise):
    """One cluster lifetime: boot, probe through the scripted kill,
    tear down.  Returns per-plane (ok, attempts) plus latencies."""
    import ray_tpu
    from ray_tpu import api as _api
    from ray_tpu._private import fault_injection as fi
    from ray_tpu._private.config import GLOBAL_CONFIG

    ray_tpu.init(num_cpus=2, object_store_memory=64 << 20, _system_config={
        "gcs_supervise": supervise,
        # Without the supervisor the head stays dead: cap how long each
        # buffered call waits so the unsupervised run finishes.
        "gcs_outage_deadline_s": args.outage_deadline_s,
        "chaos_enabled": True,
        "chaos_seed": args.seed,
        "chaos_kill_gcs_at": args.kill_at,
        "chaos_max_faults": 1,
    })
    try:
        @ray_tpu.remote
        class Probe:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        actor = Probe.remote()
        assert ray_tpu.get(actor.inc.remote(), timeout=60) == 1
        w = _api._worker

        # Drive the head's request ordinal to the scripted kill point
        # while the measurement window is open.
        def pump():
            for _ in range(2 * args.kill_at):
                try:
                    w.io.run(w.gcs.call(
                        "Kv", "kv_exists", {"ns": "bench", "key": "pump"}))
                except Exception:
                    return  # unsupervised mode: the head is gone

        pt = threading.Thread(target=pump, daemon=True)
        pt.start()

        ctrl_ok = ctrl_n = data_ok = data_n = 0
        ctrl_lat = []
        end = time.monotonic() + args.window_s
        while time.monotonic() < end:
            ctrl_n += 1
            t0 = time.perf_counter()
            try:
                w.io.run(w.gcs.call("Kv", "kv_exists",
                                    {"ns": "bench", "key": "probe"}),
                         timeout=args.outage_deadline_s + 5)
                ctrl_ok += 1
            except Exception:
                pass
            ctrl_lat.append(time.perf_counter() - t0)
            data_n += 1
            try:
                ray_tpu.get(actor.inc.remote(), timeout=5)
                data_ok += 1
            except Exception:
                pass
            time.sleep(args.probe_interval_s)
        pt.join(5)
        sup = _api._cluster["group"].supervisors
        restarts = sup[0].restarts if sup else 0
        return (ctrl_ok, ctrl_n, data_ok, data_n, ctrl_lat, restarts)
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--window-s", type=float, default=12.0,
                    help="measurement window per mode (seconds)")
    ap.add_argument("--probe-interval-s", type=float, default=0.05)
    ap.add_argument("--kill-at", type=int, default=300,
                    help="scripted GCS request ordinal to die at")
    ap.add_argument("--outage-deadline-s", type=float, default=4.0)
    ap.add_argument("--seed", type=int, default=16)
    args = ap.parse_args()

    c_ok, c_n, d_ok, d_n, lat, restarts = _run_mode(args, supervise=True)
    uc_ok, uc_n, ud_ok, ud_n, _, _ = _run_mode(args, supervise=False)

    avail_sup = c_ok / max(1, c_n)
    avail_unsup = uc_ok / max(1, uc_n)

    print(json.dumps({
        "metric": "gcs_availability_supervised",
        "value": round(avail_sup, 4),
        "unit": "fraction",
        "vs_baseline": round(avail_sup / max(avail_unsup, 1e-9), 3),
        "availability_unsupervised": round(avail_unsup, 4),
        "data_plane_availability_supervised": round(d_ok / max(1, d_n), 4),
        "data_plane_availability_unsupervised": round(
            ud_ok / max(1, ud_n), 4),
        "p99_control_ms_supervised": round(
            _percentile(lat, 0.99) * 1000, 1),
        "supervised_restarts": restarts,
        "control_probes": c_n,
        "window_s": args.window_s,
    }))


if __name__ == "__main__":
    main()
